"""Plan auditor (ISSUE 17, analysis/plan_audit.py + analysis/hlo.py).

The contract under test: ``st.audit_plan`` AOT-lowers a plan over its
committed shardings — no execution — and reports every collective in
the post-GSPMD module with modeled wire bytes, attributed back to the
expr node whose ``__sg_<digest>`` scope emitted it. Golden audits pin
the communication shape of three canonical plans (the CI tripwire the
benchmark gates mirror); the pathological traced-start dynamic slice
MUST surface the ``full_gather`` finding with node + source
provenance; the donation header check catches silently-dropped
donations; the verdict memoizes, rides the persist store across a
warm restart, renders in ``st.explain``, and powers the serve
engine's ``FLAGS.comm_budget_bytes`` admission gate.
"""

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.array import tiling as tiling_mod
from spartan_tpu.array.tiling import Tiling
from spartan_tpu.expr import base as expr_base
from spartan_tpu.expr import incremental
from spartan_tpu.obs.metrics import REGISTRY
from spartan_tpu.utils import profiling
from spartan_tpu.utils.config import FLAGS


def _counter(name):
    return REGISTRY.counter_values().get(name, 0)


def _arr(shape, tiling=None, seed=0):
    rng = np.random.RandomState(seed)
    return st.from_numpy(rng.rand(*shape).astype(np.float32),
                         tiling=tiling)


# -- golden audits (the benchmark gate's in-suite counterpart) -----------


def test_audit_dot_sharded_contract(mesh1d):
    """Row-sharded dot: the contraction all-reduces partial products
    and must NOT gather an operand — the sharding is load-bearing."""
    a = _arr((32, 32), tiling_mod.row(2), seed=1)
    b = _arr((32, 32), tiling_mod.row(2), seed=2)
    audit = st.audit_plan(st.dot(st.as_expr(a), st.as_expr(b)))
    assert audit.multiset.get("all-reduce", 0) == 1
    assert audit.multiset.get("all-gather", 0) == 0
    assert audit.comm_bytes > 0
    assert audit.findings == []
    # attribution: the collectives join back to the dot node, not
    # <unattributed>, through the __sg_ scope digests
    nodes = [r["node"] for r in audit.per_node()]
    assert any(n and "DotExpr" in n for n in nodes), nodes


def test_audit_stencil_halo_permutes_only(mesh1d):
    """H-sharded SAME-padding stencil: GSPMD lowers the halo exchange
    to two collective-permutes (up + down) — any all-gather here means
    the neighbor exchange degraded to full replication."""
    x = _arr((1, 32, 16, 4), Tiling((None, "x", None, None)), seed=3)
    k = np.random.RandomState(4).rand(3, 3, 4, 4).astype(np.float32)
    audit = st.audit_plan(st.stencil(st.as_expr(x), k))
    assert audit.multiset.get("collective-permute", 0) == 2
    assert audit.multiset.get("all-gather", 0) == 0
    assert audit.multiset.get("all-reduce", 0) == 0
    assert not [f for f in audit.findings if f.kind == "full_gather"]
    nodes = [r["node"] for r in audit.per_node()]
    assert any(n and "StencilExpr" in n for n in nodes), nodes


def test_audit_traced_start_slice_flags_full_gather(mesh2d):
    """The pathological class the auditor exists for: a traced-start
    dynamic slice of a sharded operand all-gathers the ENTIRE operand
    onto every chip. The finding must name the node and the build
    site in the incremental seam (the one sanctioned construction
    site — lint rule 15 bans it everywhere else)."""
    incremental._types()
    xs = _arr((32, 16), tiling_mod.row(2), seed=5)
    sl = incremental.DynSliceExpr(
        st.as_expr(xs),
        (expr_base.ScalarExpr(0), expr_base.ScalarExpr(0)), (4, 16))
    audit = st.audit_plan(sl)
    hits = [f for f in audit.findings if f.kind == "full_gather"]
    assert hits, [str(f) for f in audit.findings]
    f = hits[0]
    assert f.node is not None          # attributed, not <unattributed>
    assert f.source and "incremental.py" in f.source
    assert f.bytes and f.bytes >= 32 * 16 * 4  # the WHOLE leaf, per chip
    assert "docs/INCREMENTAL.md" in f.message


# -- donation header check -----------------------------------------------


def test_audit_donation_honored_and_missed(mesh2d):
    # same-shape elementwise: the executable aliases the donated slot
    y = _arr((8, 8), seed=6).evaluate()
    ok = st.audit_plan(st.as_expr(y) * 2.0, donate=[y])
    assert ok.donation["requested"] == [0]
    assert 0 in ok.donation["aliased"]
    assert not [f for f in ok.findings if f.kind == "missed_donation"]

    # scalar-out reduction: nothing to alias an (8,8) buffer against —
    # the input_output_alias header drops the request, and the audit
    # says so instead of letting the runtime copy silently
    z = _arr((8, 8), seed=7).evaluate()
    missed = st.audit_plan((st.as_expr(z) + 1.0).sum(), donate=[z])
    assert missed.donation["requested"] == [0]
    assert 0 not in missed.donation["aliased"]
    assert [f for f in missed.findings if f.kind == "missed_donation"]


# -- verdict caching ------------------------------------------------------


def test_audit_verdict_memoized(mesh2d):
    a = _arr((16, 16), tiling_mod.row(2), seed=8)
    e = st.dot(st.as_expr(a), st.as_expr(a)) + 5.0
    runs0, cached0 = _counter("audit_runs"), _counter("audit_cached")
    first = st.audit_plan(e)
    second = st.audit_plan(e)
    assert _counter("audit_runs") - runs0 == 1, \
        "repeat audits must read the memoized verdict, not recompile"
    assert _counter("audit_cached") - cached0 == 1
    assert second.multiset == first.multiset
    assert second.comm_bytes == first.comm_bytes


def test_warm_restart_restores_verdict_no_reaudit(mesh2d, tmp_path):
    """The verdict rides the persist store's plan metadata: a restart
    restores audit + executable together, and the verify-on miss path
    reads the restored verdict instead of re-lowering."""
    from spartan_tpu import persist

    FLAGS.persist_cache_dir = str(tmp_path / "store")
    expr_base.clear_compile_cache()
    persist.reset()
    prev = FLAGS.verify_evaluate
    FLAGS.verify_evaluate = True
    try:
        def build():
            a = _arr((16, 16), tiling_mod.row(2), seed=9)
            return st.dot(st.as_expr(a), st.as_expr(a)).sum()

        runs0 = _counter("audit_runs")
        build().evaluate()
        assert _counter("audit_runs") - runs0 == 1  # cold: one audit

        # simulated restart: in-memory caches dropped, disk survives
        expr_base.clear_compile_cache()
        persist.reset()
        profiling.reset_counters()
        runs1, cached1 = _counter("audit_runs"), _counter("audit_cached")
        build().evaluate()
        assert profiling.counters().get("compiles", 0) == 0
        assert _counter("audit_runs") - runs1 == 0, \
            "a persist-restored verdict must not re-audit"
        assert _counter("audit_cached") - cached1 == 1
    finally:
        FLAGS.verify_evaluate = prev


# -- surfaces -------------------------------------------------------------


def test_explain_renders_collective_table(mesh2d):
    a = _arr((16, 16), tiling_mod.row(2), seed=10)
    e = st.dot(st.as_expr(a), st.as_expr(a))
    st.audit_plan(e)
    rep = str(st.explain(e))
    assert "plan audit:" in rep
    assert "DotExpr" in rep
    assert "all-reduce" in rep


def test_comm_budget_serve_admission(mesh1d):
    """FLAGS.comm_budget_bytes gates AUDITED verdicts at submit time:
    over-budget plans are rejected with the worst finding in the
    flight record; unaudited plans pass (the budget never forces an
    AOT compile onto the submit path)."""
    from spartan_tpu.obs import flight
    from spartan_tpu.serve import CommBudgetExceeded

    a = _arr((32, 32), tiling_mod.row(2), seed=11)
    b = _arr((32, 32), tiling_mod.row(2), seed=12)
    e = st.dot(st.as_expr(a), st.as_expr(b)).sum()
    audit = st.audit_plan(e)
    assert audit.comm_bytes > 1

    prev = FLAGS.comm_budget_bytes
    try:
        FLAGS.comm_budget_bytes = 1
        with st.ServeEngine(workers=1) as eng:
            with pytest.raises(CommBudgetExceeded) as ei:
                eng.submit(e)
            assert ei.value.comm_bytes == audit.comm_bytes
            ev = [v for v in flight.events() if v.kind == "reject"
                  and v.args.get("reason") == "comm_budget"]
            assert ev and ev[-1].args.get("finding")

            # an UNAUDITED plan sails through the same budget
            fresh = (st.as_expr(a) + st.as_expr(b)).sum() * 99.0
            assert float(eng.submit(fresh).glom(timeout=60)) != 0

        FLAGS.comm_budget_bytes = int(audit.comm_bytes) + 1
        with st.ServeEngine(workers=1) as eng:
            assert np.isfinite(float(eng.submit(e).glom(timeout=60)))
    finally:
        FLAGS.comm_budget_bytes = prev
