"""Native C++ component + checkpoint tests."""

import os
import tempfile

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu import native
from spartan_tpu.array import extent, tiling
from spartan_tpu.array.extent import TileExtent
from spartan_tpu.utils import checkpoint


def test_native_builds():
    assert native.lib() is not None, "C++ extension failed to build"


def test_intersect_batch_matches_python():
    rng = np.random.RandomState(0)
    uls = rng.randint(0, 50, (200, 2)).astype(np.int64)
    lrs = uls + rng.randint(1, 20, (200, 2))
    q_ul, q_lr = (20, 20), (45, 45)
    mask, out_ul, out_lr = native.intersect_batch(uls, lrs, q_ul, q_lr)
    region = TileExtent(q_ul, q_lr, (100, 100))
    for i in range(200):
        e = TileExtent(uls[i], lrs[i], (100, 100))
        ix = e.intersection(region)
        assert mask[i] == (ix is not None)
        if ix is not None:
            assert tuple(out_ul[i]) == ix.ul
            assert tuple(out_lr[i]) == ix.lr


def test_any_overlap_and_volume():
    grid = extent.tile_grid((12, 12), (3, 3))
    uls = np.array([e.ul for e in grid], np.int64)
    lrs = np.array([e.lr for e in grid], np.int64)
    assert not native.any_overlap(uls, lrs)
    assert native.total_volume(uls, lrs) == 144
    # introduce an overlap
    uls2 = np.vstack([uls, [[0, 0]]])
    lrs2 = np.vstack([lrs, [[2, 2]]])
    assert native.any_overlap(uls2, lrs2)


def test_find_overlapping_native_path(mesh2d):
    from spartan_tpu.utils.config import FLAGS

    grid = extent.tile_grid((64, 64), (16, 16))  # 256 tiles > threshold
    region = TileExtent((10, 10), (20, 20), (64, 64))
    native_hits = extent.find_overlapping(grid, region)
    FLAGS.use_cpp_extent = False
    try:
        py_hits = extent.find_overlapping(grid, region)
    finally:
        FLAGS.use_cpp_extent = True
    assert native_hits == py_hits
    assert extent.is_complete((64, 64), grid)


def test_blob_roundtrip():
    rng = np.random.RandomState(1)
    arrays = [rng.rand(16, 8).astype(np.float32) for _ in range(5)]
    with tempfile.TemporaryDirectory() as d:
        paths = [os.path.join(d, f"b{i}.bin") for i in range(5)]
        native.write_blobs(paths, arrays)
        outs = [np.empty_like(a) for a in arrays]
        native.read_blobs(paths, outs)
        for a, b in zip(arrays, outs):
            np.testing.assert_array_equal(a, b)


def test_blob_read_missing_fails():
    with tempfile.TemporaryDirectory() as d:
        with pytest.raises(IOError):
            native.read_blobs([os.path.join(d, "nope.bin")],
                              [np.empty(4, np.float32)])


def test_checkpoint_roundtrip(mesh2d):
    x = np.random.RandomState(2).rand(16, 8).astype(np.float32)
    arr = st.from_numpy(x, tiling=tiling.block(2)).evaluate()
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save(d, arr)
        back = checkpoint.load(d)
        np.testing.assert_array_equal(back.glom(), x)
        assert back.tiling == arr.tiling
        # load with an explicit different tiling
        back2 = checkpoint.load(d, tiling=tiling.row(2))
        np.testing.assert_array_equal(back2.glom(), x)
        assert back2.tiling == tiling.row(2)


def test_checkpoint_replicated_writes_once(mesh2d):
    x = np.random.RandomState(3).rand(8, 8).astype(np.float32)
    arr = st.from_numpy(x, tiling=tiling.replicated(2))
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save(d, arr)
        blobs = [f for f in os.listdir(d) if f.endswith(".bin")]
        assert len(blobs) == 1  # replicated shards deduped
        np.testing.assert_array_equal(checkpoint.load(d).glom(), x)


def test_checkpoint_tree(mesh2d):
    rng = np.random.RandomState(4)
    state = {"w": st.from_numpy(rng.rand(8, 4).astype(np.float32)),
             "b": st.from_numpy(rng.rand(4).astype(np.float32))}
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_tree(d, state)
        back = checkpoint.load_tree(d)
        assert set(back) == {"w", "b"}
        np.testing.assert_array_equal(back["w"].glom(),
                                      state["w"].glom())
        np.testing.assert_array_equal(back["b"].glom(),
                                      state["b"].glom())
