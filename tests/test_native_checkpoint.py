"""Native C++ component + checkpoint tests."""

import os
import tempfile

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu import native
from spartan_tpu.array import extent, tiling
from spartan_tpu.array.extent import TileExtent
from spartan_tpu.utils import checkpoint


def test_native_builds():
    assert native.lib() is not None, "C++ extension failed to build"


def test_intersect_batch_matches_python():
    rng = np.random.RandomState(0)
    uls = rng.randint(0, 50, (200, 2)).astype(np.int64)
    lrs = uls + rng.randint(1, 20, (200, 2))
    q_ul, q_lr = (20, 20), (45, 45)
    mask, out_ul, out_lr = native.intersect_batch(uls, lrs, q_ul, q_lr)
    region = TileExtent(q_ul, q_lr, (100, 100))
    for i in range(200):
        e = TileExtent(uls[i], lrs[i], (100, 100))
        ix = e.intersection(region)
        assert mask[i] == (ix is not None)
        if ix is not None:
            assert tuple(out_ul[i]) == ix.ul
            assert tuple(out_lr[i]) == ix.lr


def test_any_overlap_and_volume():
    grid = extent.tile_grid((12, 12), (3, 3))
    uls = np.array([e.ul for e in grid], np.int64)
    lrs = np.array([e.lr for e in grid], np.int64)
    assert not native.any_overlap(uls, lrs)
    assert native.total_volume(uls, lrs) == 144
    # introduce an overlap
    uls2 = np.vstack([uls, [[0, 0]]])
    lrs2 = np.vstack([lrs, [[2, 2]]])
    assert native.any_overlap(uls2, lrs2)


def test_find_overlapping_native_path(mesh2d):
    from spartan_tpu.utils.config import FLAGS

    grid = extent.tile_grid((64, 64), (16, 16))  # 256 tiles > threshold
    region = TileExtent((10, 10), (20, 20), (64, 64))
    native_hits = extent.find_overlapping(grid, region)
    FLAGS.use_cpp_extent = False
    try:
        py_hits = extent.find_overlapping(grid, region)
    finally:
        FLAGS.use_cpp_extent = True
    assert native_hits == py_hits
    assert extent.is_complete((64, 64), grid)


def test_blob_roundtrip():
    rng = np.random.RandomState(1)
    arrays = [rng.rand(16, 8).astype(np.float32) for _ in range(5)]
    with tempfile.TemporaryDirectory() as d:
        paths = [os.path.join(d, f"b{i}.bin") for i in range(5)]
        native.write_blobs(paths, arrays)
        outs = [np.empty_like(a) for a in arrays]
        native.read_blobs(paths, outs)
        for a, b in zip(arrays, outs):
            np.testing.assert_array_equal(a, b)


def test_blob_read_missing_fails():
    with tempfile.TemporaryDirectory() as d:
        with pytest.raises(IOError):
            native.read_blobs([os.path.join(d, "nope.bin")],
                              [np.empty(4, np.float32)])


def test_checkpoint_roundtrip(mesh2d):
    x = np.random.RandomState(2).rand(16, 8).astype(np.float32)
    arr = st.from_numpy(x, tiling=tiling.block(2)).evaluate()
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save(d, arr)
        back = checkpoint.load(d)
        np.testing.assert_array_equal(back.glom(), x)
        assert back.tiling == arr.tiling
        # load with an explicit different tiling
        back2 = checkpoint.load(d, tiling=tiling.row(2))
        np.testing.assert_array_equal(back2.glom(), x)
        assert back2.tiling == tiling.row(2)


def test_checkpoint_replicated_writes_once(mesh2d):
    x = np.random.RandomState(3).rand(8, 8).astype(np.float32)
    arr = st.from_numpy(x, tiling=tiling.replicated(2))
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save(d, arr)
        blobs = [f for f in os.listdir(d) if f.endswith(".bin")]
        assert len(blobs) == 1  # replicated shards deduped
        np.testing.assert_array_equal(checkpoint.load(d).glom(), x)


def test_checkpoint_tree(mesh2d):
    rng = np.random.RandomState(4)
    state = {"w": st.from_numpy(rng.rand(8, 4).astype(np.float32)),
             "b": st.from_numpy(rng.rand(4).astype(np.float32))}
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_tree(d, state)
        back = checkpoint.load_tree(d)
        assert set(back) == {"w", "b"}
        np.testing.assert_array_equal(back["w"].glom(),
                                      state["w"].glom())
        np.testing.assert_array_equal(back["b"].glom(),
                                      state["b"].glom())


def test_sparse_checkpoint_roundtrip(tmp_path, mesh1d):
    """Sparse save/load: entry shards round-trip and the loaded matrix
    re-shards onto the current mesh with identical semantics."""
    import scipy.sparse as ss

    from spartan_tpu.array.sparse import SparseDistArray
    from spartan_tpu.utils.checkpoint import load_sparse, save_sparse

    rng = np.random.RandomState(21)
    n, m, nnz = 40, 28, 150
    r = rng.randint(0, n, nnz)
    c = rng.randint(0, m, nnz)
    v = rng.rand(nnz).astype(np.float32)
    sp = SparseDistArray.from_coo(r, c, v, (n, m))
    save_sparse(str(tmp_path / "sp"), sp)
    sp2 = load_sparse(str(tmp_path / "sp"))
    assert sp2.shape == sp.shape and sp2.nnz == sp.nnz
    oracle = ss.coo_matrix((v, (r, c)), shape=(n, m)).toarray()
    np.testing.assert_allclose(sp2.glom(), oracle, rtol=1e-6)
    x = rng.rand(m).astype(np.float32)
    np.testing.assert_allclose(np.asarray(sp2.spmv(x, impl="sharded")),
                               oracle @ x, rtol=1e-4, atol=1e-5)


def test_sparse_checkpoint_cross_mesh(tmp_path):
    """Elastic restart: save on a 2-device mesh, load on 8 devices —
    the entry axis re-pads for the new mesh so the sharded paths work."""
    import jax
    import scipy.sparse as ss

    from spartan_tpu.array.sparse import SparseDistArray
    from spartan_tpu.parallel import mesh as mesh_mod
    from spartan_tpu.utils.checkpoint import load_sparse, save_sparse

    rng = np.random.RandomState(22)
    n, m, nnz = 30, 20, 150
    r = rng.randint(0, n, nnz)
    c = rng.randint(0, m, nnz)
    v = rng.rand(nnz).astype(np.float32)
    oracle = ss.coo_matrix((v, (r, c)), shape=(n, m)).toarray()

    m2 = mesh_mod.build_mesh(jax.devices()[:2], shape=(2, 1))
    with mesh_mod.use_mesh(m2):
        sp = SparseDistArray.from_coo(r, c, v, (n, m))
        save_sparse(str(tmp_path / "sp"), sp)

    m8 = mesh_mod.build_mesh(jax.devices(), shape=(8, 1))
    with mesh_mod.use_mesh(m8):
        sp2 = load_sparse(str(tmp_path / "sp"))
        assert sp2.nse % 8 == 0, sp2.nse
        np.testing.assert_allclose(sp2.glom(), oracle, rtol=1e-6)
        x = rng.rand(m).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sp2.spmv(x, impl="sharded")), oracle @ x,
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sp2.rsums()),
                                   oracle.sum(axis=1), rtol=1e-4,
                                   atol=1e-5)


def test_sparse_load_bounded_host_residency(tmp_path, mesh1d,
                                            monkeypatch):
    """Device-resident sparse load (round-4 verdict Missing #4): no
    single host read covers more than one target shard of the entry
    axis — full nnz is never materialized on host."""
    import scipy.sparse as ss

    from spartan_tpu.array.sparse import SparseDistArray
    from spartan_tpu.utils import checkpoint

    rng = np.random.RandomState(23)
    n, m, nnz = 64, 64, 1000
    r = rng.randint(0, n, nnz)
    c = rng.randint(0, m, nnz)
    v = rng.rand(nnz).astype(np.float32)
    sp = SparseDistArray.from_coo(r, c, v, (n, m))
    checkpoint.save_sparse(str(tmp_path / "sp"), sp)

    lengths = []
    real = checkpoint._read_range

    def spy(dirpath, manifest, start, stop, dtype, nthreads=8):
        lengths.append(stop - start)
        return real(dirpath, manifest, start, stop, dtype, nthreads)

    monkeypatch.setattr(checkpoint, "_read_range", spy)
    sp2 = checkpoint.load_sparse(str(tmp_path / "sp"))
    total = int(sp.data.shape[0])
    assert lengths, "shard-wise reader was not used"
    assert max(lengths) <= -(-total // 8), \
        f"host read of {max(lengths)} elements > one shard"
    oracle = ss.coo_matrix((v, (r, c)), shape=(n, m)).toarray()
    np.testing.assert_allclose(sp2.glom(), oracle, rtol=1e-6)
    assert sp2.nnz == sp.nnz
