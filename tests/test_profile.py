"""Device-time attribution profiler (ISSUE 11): st.profile attributes
the whole-plan device wall to named expr nodes on the {map, dot,
reduce, loop} matrix, st.explain shows measured device time next to
modeled cost, sampling keeps served results bit-equal under concurrent
clients, the ledger grows device columns fit_profile calibrates from,
and the obs stack stays tear-free under concurrent submitters."""

import json
import threading

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.expr import base
from spartan_tpu.obs import flight, ledger
from spartan_tpu.obs import profile as profile_mod
from spartan_tpu.obs.explain import key_hash
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _setup(mesh1d):
    saved = {n: getattr(FLAGS, n) for n in (
        "profile_sample_every", "profile_tier", "profile_max_nodes",
        "cost_ledger", "trace", "flightrec")}
    FLAGS.cost_ledger = True
    FLAGS.profile_sample_every = 0
    FLAGS.profile_tier = "auto"
    profile_mod.reset()
    ledger.set_profile(None)
    ledger.reset()
    flight.clear()
    st.serve.shutdown_default()
    yield
    st.serve.shutdown_default()
    profile_mod.reset()
    ledger.set_profile(None)
    ledger.reset()
    flight.clear()
    for n, v in saved.items():
        setattr(FLAGS, n, v)


def _leaves(seed=0, n=256, d=64):
    rng = np.random.RandomState(seed)
    x = st.as_expr(rng.rand(n, d).astype(np.float32)).evaluate()
    y = st.as_expr(rng.rand(n, d).astype(np.float32)).evaluate()
    a = st.as_expr(rng.rand(128, 128).astype(np.float32)).evaluate()
    b = st.as_expr(rng.rand(128, 128).astype(np.float32)).evaluate()
    return x, y, a, b


def _matrix(x, y, a, b):
    """Fresh structurally-distinct roots per call: one per op family
    of the acceptance matrix."""
    return {
        "map": (st.as_expr(x) + st.as_expr(y)) * 3.0 - st.as_expr(x),
        "dot": st.dot(st.as_expr(a), st.as_expr(b)),
        "reduce": st.as_expr(x).sum(axis=0),
        "loop": st.loop(3, lambda c: c * 0.5 + 1.0, st.as_expr(a)),
    }


# -- the acceptance criterion --------------------------------------------


def test_attribution_matrix_cpu():
    """>=90% of the measured whole-plan device wall attributed to
    named expr nodes on every family, residual reported as
    unattributed, every node keyed by a _sig digest."""
    x, y, a, b = _leaves()
    for name, expr in _matrix(x, y, a, b).items():
        prof = st.profile(expr, reps=3)
        assert prof.tier in ("replay", "xplane"), (name, prof.tier)
        assert prof.wall_s > 0, name
        assert prof.nodes, name
        assert prof.attributed_fraction >= 0.9, (
            name, prof.attributed_fraction, str(prof))
        # the residual is reported, not silently dropped
        assert prof.unattributed_s >= 0.0
        assert abs(prof.attributed_s + prof.unattributed_s
                   - max(prof.wall_s, prof.attributed_s)) < 1e-9
        for node in prof.nodes:
            assert node["digest"], (name, node)
            assert node["seconds"] > 0
            assert "modeled_cost" in node  # measured NEXT TO modeled
            assert node["op_class"]


def test_profile_report_shapes():
    x, y, a, b = _leaves()
    prof = st.profile(_matrix(x, y, a, b)["map"], reps=2)
    d = prof.to_dict()
    json.dumps(d)  # JSON-serializable end to end
    assert d["tier"] == prof.tier
    assert d["class_seconds"]
    assert prof.top(1) and prof.top(1)[0]["seconds"] == max(
        n["seconds"] for n in prof.nodes)
    assert "device profile" in str(prof)


def test_explain_shows_measured_next_to_modeled():
    x, y, a, b = _leaves()
    st.profile(_matrix(x, y, a, b)["dot"], reps=2)
    rep = st.explain(_matrix(x, y, a, b)["dot"], cost=False)
    dp = rep.data.get("device_profile")
    assert dp is not None
    assert dp["nodes"]
    for node in dp["nodes"]:  # every attributed node: measured + modeled
        assert node["seconds"] > 0
        assert "modeled_cost" in node
    text = str(rep)
    assert "device profile" in text
    assert "attributed" in text


def test_profile_preplans_like_explain():
    """Profiling a never-evaluated expr builds (and caches) its plan —
    the next evaluate is a plan-cache hit."""
    from spartan_tpu.utils import profiling

    x, y, a, b = _leaves()
    e = _matrix(x, y, a, b)["reduce"]
    profiling.reset_counters()
    st.profile(e, reps=1)
    before = profiling.counters().get("plan_hits", 0)
    _matrix(x, y, a, b)["reduce"].evaluate()
    assert profiling.counters().get("plan_hits", 0) == before + 1


def test_profile_result_matches_evaluate():
    """The profiled sub-plans replay the same computation: profiling
    does not disturb the evaluated result."""
    x, y, a, b = _leaves()
    ref = _matrix(x, y, a, b)["map"].glom()
    st.profile(_matrix(x, y, a, b)["map"], reps=1)
    got = _matrix(x, y, a, b)["map"].glom()
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_scope_names_carry_digest():
    """Inside a naming session every node's named_scope label carries
    its structural-signature digest — the trace-parse join key — and
    the digest matches the signing context's memoized signature."""
    x, y, a, b = _leaves()
    e = _matrix(x, y, a, b)["map"]
    dag = e.optimized()
    with profile_mod.naming_session():
        name = profile_mod.scope_name(dag)
        assert profile_mod._SCOPE_MARK in name
        digest = name.split(profile_mod._SCOPE_MARK, 1)[1]
        ctx = base._SigCtx()
        ctx.of(dag)
        assert digest == key_hash(ctx._memo[dag._id])
    # outside a session the legacy label (no digest) is unchanged
    assert profile_mod._SCOPE_MARK not in profile_mod.scope_name(dag)


def test_profile_export_merges_host_and_device(tmp_path):
    x, y, a, b = _leaves()
    st.profile(_matrix(x, y, a, b)["map"], reps=1)
    path = tmp_path / "merged.json"
    doc = st.profile_export(str(path))
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["traceEvents"]
    device = [ev for ev in loaded["traceEvents"]
              if ev.get("tid") == 1_000_000]
    # the device track: thread metadata + >=1 attributed segment with
    # the digest in the event name
    assert any(ev.get("ph") == "M" for ev in device)
    segs = [ev for ev in device if ev.get("ph") == "X"]
    assert segs and any("[" in ev["name"] for ev in segs)
    # host spans from the trace ring are in the same document
    assert any(ev.get("tid") != 1_000_000 and ev.get("ph") == "X"
               for ev in loaded["traceEvents"])
    assert doc["traceEvents"]


def test_xplane_tier_explicit_raises_or_measures():
    """tier='xplane' either attributes from a real capture or raises
    the documented error — never silently falls back."""
    x, y, a, b = _leaves()
    try:
        prof = st.profile(_matrix(x, y, a, b)["map"], tier="xplane",
                          reps=1)
    except RuntimeError as e:
        assert "xplane" in str(e)
    else:
        assert prof.tier == "xplane"
        assert prof.nodes


# -- sampled continuous profiling ----------------------------------------


def _kstep(pts, c, k=8):
    from spartan_tpu.examples.kmeans import kmeans_step
    from spartan_tpu.expr.base import ValExpr

    return kmeans_step(pts, ValExpr(c), k)


def test_sampler_counts_every_nth():
    rng = np.random.RandomState(1)
    pts = st.from_numpy(rng.rand(128, 16).astype(np.float32))
    c = st.as_expr(rng.rand(8, 16).astype(np.float32)).evaluate()
    c = _kstep(pts, c).evaluate()  # compile run (never sampled)
    FLAGS.profile_sample_every = 3
    before = st.metrics()["counters"].get("profile_samples", 0)
    for _ in range(7):  # 7 warm dispatches -> samples at 3 and 6
        c = _kstep(pts, c).evaluate()
    FLAGS.profile_sample_every = 0
    took = st.metrics()["counters"].get("profile_samples", 0) - before
    assert took == 2


def test_sampled_results_bit_equal_and_no_key_changes():
    """The sampling wrapper is dispatch-time only: same plan key, same
    executable, bit-equal results sampled vs unsampled."""
    rng = np.random.RandomState(2)
    pts = st.from_numpy(rng.rand(128, 16).astype(np.float32))
    c0 = st.as_expr(rng.rand(8, 16).astype(np.float32)).evaluate()

    key_off, _ = base.plan_signature(_kstep(pts, c0))
    ref = _kstep(pts, c0).evaluate().glom()

    FLAGS.profile_sample_every = 1
    key_on, _ = base.plan_signature(_kstep(pts, c0))
    got = _kstep(pts, c0).evaluate().glom()
    FLAGS.profile_sample_every = 0

    assert key_on == key_off  # no plan/compile-key changes
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_sampled_serving_bit_equal_16_clients():
    """The ISSUE-11 acceptance leg: profile_sample_every=4 under 16
    concurrent clients — every future resolves bit-equal to the
    unsampled serial result."""
    rng = np.random.RandomState(3)
    pts = st.from_numpy(rng.rand(128, 16).astype(np.float32))
    c0 = st.as_expr(rng.rand(8, 16).astype(np.float32)).evaluate()
    ref = np.asarray(_kstep(pts, c0).evaluate().glom())

    FLAGS.profile_sample_every = 4
    eng = st.ServeEngine(workers=4)
    with eng:
        futs = [eng.submit(_kstep(pts, c0)) for _ in range(16)]
        outs = [np.asarray(f.glom()) for f in futs]
    FLAGS.profile_sample_every = 0
    for out in outs:
        np.testing.assert_array_equal(ref, out)


def test_sampled_requests_stamped_in_flight_recorder():
    rng = np.random.RandomState(4)
    pts = st.from_numpy(rng.rand(128, 16).astype(np.float32))
    c0 = st.as_expr(rng.rand(8, 16).astype(np.float32)).evaluate()
    _kstep(pts, c0).evaluate()  # warm: the serve dispatches all hit
    _kstep(pts, c0).evaluate()

    FLAGS.profile_sample_every = 1
    eng = st.ServeEngine(workers=2, coalesce_requests=False)
    with eng:
        futs = [eng.submit(_kstep(pts, c0)) for _ in range(4)]
        for f in futs:
            f.glom()
    FLAGS.profile_sample_every = 0
    rec = st.flightrec()
    stamped = [r for r in rec["requests"].values() if "profiled" in r]
    assert stamped
    p = stamped[0]["profiled"]
    assert p["tier"] in ("replay", "xplane")
    assert p["device_s"] >= 0


def test_ledger_device_columns_and_device_fit():
    """Sampled per-node device seconds land as per-op-class DEVICE
    columns and fit_profile calibrates from them (meta.source says
    so)."""
    x, y, a, b = _leaves()
    prof = st.profile(_matrix(x, y, a, b)["dot"], reps=2)
    snap = st.ledger()
    entry = snap["plans"].get(prof.plan_digest)
    assert entry is not None
    dev = entry["measured"]["device"]
    assert dev is not None
    assert dev["samples"] >= 1
    assert dev["class_seconds_mean"]
    assert dev["attributed_mean_s"] > 0
    fitted = st.fit_profile()
    assert fitted is not None
    assert fitted.meta["source"] == "device_time"
    assert fitted.meta["device_rows"] >= 1
    assert fitted.factors


def test_profile_schema_roundtrip_versions(tmp_path):
    """st.save_profile writes v2; st.load_profile accepts BOTH v2 and
    pre-device-column v1 files (version field + defaulting)."""
    p2 = ledger.CalibrationProfile(
        {"map": 1.5, "contraction": 0.7},
        meta={"source": "device_time", "device_rows": 4})
    path2 = tmp_path / "v2.json"
    st.save_profile(str(path2), p2)
    with open(path2) as f:
        on_disk = json.load(f)
    assert on_disk["version"] == ledger.PROFILE_VERSION == 2
    loaded = st.load_profile(str(path2))
    assert loaded.factors == p2.factors
    assert loaded.meta["source"] == "device_time"
    assert loaded.fingerprint() == p2.fingerprint()

    # a v1 file (pre-device-column schema: no source/device_rows)
    path1 = tmp_path / "v1.json"
    with open(path1, "w") as f:
        json.dump({"version": 1, "factors": {"reshard": 4.1},
                   "meta": {"fitted_from_plans": 3}}, f)
    old = st.load_profile(str(path1))
    assert old.factors == {"reshard": 4.1}
    assert old.meta["source"] == "host_wall"  # defaulted
    assert old.meta["device_rows"] == 0
    assert old.meta["fitted_from_plans"] == 3

    # versions beyond this build still refuse loudly
    path9 = tmp_path / "v9.json"
    with open(path9, "w") as f:
        json.dump({"version": 9, "factors": {}}, f)
    with pytest.raises(ValueError, match="version"):
        st.load_profile(str(path9))
    ledger.set_profile(None)


# -- obs thread-safety under serving (ISSUE-11 satellite) ----------------


def test_obs_thread_safety_under_concurrent_submitters():
    """Trace ring + flight recorder + sampled profiler hit by N
    concurrent evaluate_async submitters: no deadlock, no torn
    records, results bit-equal to serial."""
    rng = np.random.RandomState(5)
    pts = st.from_numpy(rng.rand(128, 16).astype(np.float32))
    c0 = st.as_expr(rng.rand(8, 16).astype(np.float32)).evaluate()
    ref = np.asarray(_kstep(pts, c0).evaluate().glom())

    FLAGS.profile_sample_every = 2
    n_threads, per_thread = 8, 3
    results = [[None] * per_thread for _ in range(n_threads)]
    errors = []

    eng = st.ServeEngine(workers=4)

    def client(i):
        try:
            for j in range(per_thread):
                fut = eng.submit(_kstep(pts, c0))
                results[i][j] = np.asarray(fut.glom())
        except Exception as e:  # noqa: BLE001 - collected for assert
            errors.append(e)

    with eng:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "deadlock"
    FLAGS.profile_sample_every = 0
    assert not errors, errors
    for row in results:
        for out in row:
            np.testing.assert_array_equal(ref, out)

    # no torn flight records: every resolve carries its full latency
    # decomposition, every profiled stamp its full field set
    rec = st.flightrec()
    resolves = [e for e in rec["events"] if e["kind"] == "resolve"]
    assert resolves
    for e in resolves:
        for k in ("queue_wait_s", "coalesce_wait_s", "dispatch_s"):
            assert e.get(k) is not None and e[k] >= 0
    for r in rec["requests"].values():
        if "profiled" in r:
            assert r["profiled"]["tier"] in ("replay", "xplane")
            assert r["profiled"]["device_s"] is not None
    # the trace ring survived concurrent appends (snapshot iterates)
    assert st.obs.trace_events() is not None
