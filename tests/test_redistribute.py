"""Cost-modeled redistribution planner (ISSUE 10): schedule
enumeration, explicit shard_map lowering correctness, plan-key
separation under the flag, the explain/ledger/memory surfaces, and the
GSPMD fallback contract."""

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.array import tiling
from spartan_tpu.expr import base
from spartan_tpu.obs import ledger
from spartan_tpu.obs.explain import key_hash
from spartan_tpu.parallel import mesh as mesh_mod
from spartan_tpu.parallel import redistribute as rd
from spartan_tpu.utils import profiling as prof
from spartan_tpu.utils.config import FLAGS

jax = mesh_mod.jax


@pytest.fixture(autouse=True)
def _flags():
    yield
    ledger.set_profile(None)
    ledger.reset()
    FLAGS.reset_all()


# -- schedule enumeration + decision -------------------------------------


def test_all_to_all_beats_gather_slice(mesh2d):
    """The canonical win: moving a mesh axis between array axes is ONE
    all_to_all (each chip keeps 1/p), 4x cheaper than the
    gather-everything reference GSPMD's generic lowering models."""
    m = mesh_mod.get_mesh()
    d = rd.decide(tiling.row(2), tiling.col_t(2), (16, 16),
                  np.float32, m)
    assert d is not None and d.explicit
    assert d.schedule.describe() == "all_to_all[x:0->1]"
    assert d.cost < d.gspmd_cost
    # and the modeled cost sits exactly on the receive floor
    from spartan_tpu.expr.tiling_cost import reshard_cost

    nb = 16 * 16 * 4
    assert d.cost == pytest.approx(
        reshard_cost(tiling.row(2), tiling.col_t(2), nb, m))


def test_slice_first_halves_gather_traffic(mesh2d):
    """row -> col: slicing the destination axis BEFORE gathering the
    source axis halves the gather's per-chip bytes — the enumeration
    must find the reordering. But gather/slice-only routes stay on the
    GSPMD path (its own lowering finds them; the measured CPU A/B
    shows the explicit form is never cheaper there)."""
    m = mesh_mod.get_mesh()
    d = rd.decide(tiling.row(2), tiling.col(2), (16, 16),
                  np.float32, m)
    assert d is not None
    assert d.schedule.describe() == "slice[y:1] + all_gather[x:0]"
    assert d.cost == pytest.approx(d.gspmd_cost / 2)
    assert not d.explicit
    assert "multi-step" in d.reason


def test_gather_only_edges_stay_gspmd(mesh2d):
    """sharded -> replicated is exactly what GSPMD's all-gather does:
    no modeled win, the portable fallback is kept."""
    m = mesh_mod.get_mesh()
    d = rd.decide(tiling.row(2), tiling.replicated(2), (16, 16),
                  np.float32, m)
    assert d is not None and not d.explicit
    d2 = rd.decide(tiling.replicated(2), tiling.row(2), (16, 16),
                   np.float32, m)
    assert d2 is not None and not d2.explicit  # local carve, 0 bytes


def test_indivisible_shapes_fall_back(mesh2d):
    """A winning schedule whose intermediate doesn't divide the shape
    evenly must NOT be emitted (GSPMD pads; shard_map cannot)."""
    m = mesh_mod.get_mesh()
    d = rd.decide(tiling.row(2), tiling.col_t(2), (17, 16),
                  np.float32, m)
    assert d is not None and not d.explicit
    assert "indivisible" in d.reason


def test_schedule_staging_tracks_peak_intermediate(mesh2d):
    """block -> block_t routes through a partial gather: the
    schedule's peak staging (1/4 of the array per chip) is HIGHER than
    the destination-shard fraction (1/8) the legacy model assumed, and
    far below the full-gather canonical route (1.0)."""
    m = mesh_mod.get_mesh()
    frac = rd.staging_frac(tiling.block(2), tiling.block_t(2), m)
    assert frac == pytest.approx(0.25)
    # memory governor consumes it: same quantity through the seam
    from spartan_tpu.resilience.memory import _staging_bytes

    x = st.from_numpy(np.ones((16, 16), np.float32),
                      tiling=tiling.block(2))
    child = st.as_expr(x)
    FLAGS.redistribution_planner = True
    planned = _staging_bytes(child, tiling.block_t(2), m)
    FLAGS.redistribution_planner = False
    legacy = _staging_bytes(child, tiling.block_t(2), m)
    nb = 16 * 16 * 4
    assert planned == pytest.approx(0.25 * nb)
    assert legacy == pytest.approx(nb / 8)


# -- explicit shard_map lowering ------------------------------------------


# (src, dst, explicit expected): all_to_all-carrying transitions are
# the explicit winners; gather/slice-only routes stay on GSPMD but
# their schedules must still apply bit-exactly
_PAIRS = [
    (tiling.row(2), tiling.col_t(2), True),
    (tiling.col_t(2), tiling.row(2), True),
    (tiling.row(2), tiling.col(2), False),
    (tiling.block(2), tiling.block_t(2), False),
    (tiling.col(2), tiling.row_t(2), True),
    (tiling.col(2), tiling.block(2), False),
]


@pytest.mark.parametrize(
    "src,dst,explicit", _PAIRS,
    ids=[f"{s.axes}->{d.axes}" for s, d, _ in _PAIRS])
def test_apply_schedule_bit_exact(mesh2d, src, dst, explicit):
    """Every schedule is a pure data movement: bit-equal round trip
    and the exact destination sharding."""
    m = mesh_mod.get_mesh()
    x = np.random.RandomState(0).rand(16, 16).astype(np.float32)
    d = rd.decide(src, dst, x.shape, x.dtype, m)
    assert d is not None, (src.axes, dst.axes)
    assert d.explicit == explicit, d.reason
    arr = jax.device_put(x, src.sharding(m))
    out = jax.jit(
        lambda v: rd.apply_schedule(v, d.schedule, src, dst, m))(arr)
    np.testing.assert_array_equal(np.asarray(out), x)
    assert out.sharding.is_equivalent_to(dst.sharding(m), 2)


def test_constrain_fallback_matches_planner_off(mesh2d):
    """With the planner off (the default), constrain() IS
    with_sharding_constraint — same results, no explicit counters."""
    x = np.random.RandomState(1).rand(16, 16).astype(np.float32)

    def run():
        e = st.from_numpy(x, tiling=tiling.col_t(2))
        return np.asarray((st.as_expr(e) * 2.0).glom())

    assert not FLAGS.redistribution_planner
    prof.reset_counters()
    np.testing.assert_array_equal(run(), x * 2.0)
    assert prof.counters().get("redistribute_explicit", 0) == 0


# -- plan-key separation + end-to-end equivalence (acceptance) -----------


def _gemm_pipeline(a, b):
    # transpose + GEMM layout flip: the transposed operand lands
    # col_t-sharded while the plan wants it row-sharded — the
    # one-all_to_all explicit winner
    ea = st.from_numpy(a, tiling=tiling.row(2))
    eb = st.from_numpy(b, tiling=tiling.col(2))
    return st.dot(ea.T, eb) + 1.0


def test_plan_key_separation_and_allclose(mesh2d):
    """Acceptance: planner on vs off produce DISTINCT plan-cache keys,
    never share compiled executables, and evaluate allclose."""
    rng = np.random.RandomState(0)
    a = rng.rand(32, 32).astype(np.float32)
    b = rng.rand(32, 32).astype(np.float32)

    FLAGS.redistribution_planner = False
    k_off = base.plan_signature(_gemm_pipeline(a, b))[0]
    off = np.asarray(_gemm_pipeline(a, b).glom())
    FLAGS.redistribution_planner = True
    k_on = base.plan_signature(_gemm_pipeline(a, b))[0]
    prof.reset_counters()
    on = np.asarray(_gemm_pipeline(a, b).glom())

    assert k_on != k_off
    p_off, p_on = base.lookup_plan(k_off), base.lookup_plan(k_on)
    assert p_off is not None and p_on is not None
    assert p_off is not p_on and p_off.key != p_on.key
    assert p_off.traced is not p_on.traced
    np.testing.assert_allclose(on, off, rtol=1e-4)
    # at least one edge really lowered through an explicit schedule
    assert prof.counters().get("redistribute_explicit", 0) >= 1


def test_explicit_elementwise_bit_equal(mesh2d):
    """Where no psum reordering is involved (pure data movement around
    an elementwise kernel) the planner-on result is BIT-equal to the
    GSPMD arm."""
    from spartan_tpu.expr.map2 import shard_map2

    x = np.random.RandomState(2).rand(16, 16).astype(np.float32)

    def run():
        # operand col_t (None,'x'); kernel wants row ('x',None): the
        # reshard edge is the one-all_to_all explicit winner
        arr = st.from_numpy(x, tiling=tiling.col_t(2))
        e = shard_map2([arr], lambda b: b * 2.0 + 1.0,
                       [tiling.row(2)], tiling.row(2), x.shape)
        return np.asarray(e.glom())

    FLAGS.redistribution_planner = False
    off = run()
    FLAGS.redistribution_planner = True
    prof.reset_counters()
    on = run()
    assert prof.counters().get("redistribute_explicit", 0) >= 1
    np.testing.assert_array_equal(on, off)
    np.testing.assert_array_equal(on, x * 2.0 + 1.0)


def test_explicit_edge_bytes_beat_gspmd_on_cpu(mesh2d):
    """Acceptance: an explicitly-scheduled edge's compiled bytes
    (``compiled_cost_analysis``) are <= the GSPMD-implicit arm's —
    the all_to_all decomposition moves shards where GSPMD's generic
    lowering materializes a gathered axis."""
    import jax as jax_mod

    from spartan_tpu.obs.explain import compiled_cost_analysis

    m = mesh_mod.get_mesh()
    src, dst = tiling.row(2), tiling.col_t(2)
    n = 256
    x = np.random.RandomState(0).rand(n, n).astype(np.float32)
    d = rd.decide(src, dst, x.shape, x.dtype, m)
    assert d is not None and d.explicit
    spec = jax_mod.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=src.sharding(m))
    f_gspmd = jax_mod.jit(lambda v: jax_mod.lax.with_sharding_constraint(
        v, dst.sharding(m)) * 1.0)
    f_expl = jax_mod.jit(lambda v: rd.apply_schedule(
        v, d.schedule, src, dst, m) * 1.0)
    b_gspmd = compiled_cost_analysis(
        f_gspmd.lower(spec).compile()).get("bytes accessed")
    b_expl = compiled_cost_analysis(
        f_expl.lower(spec).compile()).get("bytes accessed")
    assert b_gspmd and b_expl
    assert b_expl <= b_gspmd
    # the arms compute the same thing
    arr = jax_mod.device_put(x, src.sharding(m))
    np.testing.assert_array_equal(np.asarray(f_gspmd(arr)),
                                  np.asarray(f_expl(arr)))


# -- observability surfaces ----------------------------------------------


def test_explain_names_schedule_and_path(mesh2d):
    """The reshard-edge report names the chosen schedule, its modeled
    cost, and the explicit-vs-gspmd path — the A/B in one call."""
    rng = np.random.RandomState(3)
    a = rng.rand(32, 32).astype(np.float32)
    FLAGS.redistribution_planner = True
    rep = st.explain(_gemm_pipeline(a, a), cost=False)
    edges = rep.reshard_edges
    assert edges, "expected planned reshard edges"
    planned = [e for e in edges if "schedule" in e]
    assert planned
    assert all(e["path"] in ("explicit", "gspmd") for e in planned)
    assert any(e["path"] == "explicit" for e in planned)
    assert all(e["modeled_cost"] >= 0 for e in planned)
    text = str(rep)
    assert " via " in text and "[explicit" in text


def test_ledger_calibrates_per_collective_classes(mesh2d):
    """The cost ledger's component decomposition carries the new
    per-collective classes under the planner, and fit_profile fits
    factors for them — st.ledger closes the loop per collective."""
    FLAGS.redistribution_planner = True
    FLAGS.cost_ledger = True
    ledger.reset()
    rng = np.random.RandomState(4)
    a = rng.rand(32, 32).astype(np.float32)
    b = rng.rand(32, 32).astype(np.float32)

    def psum_gemm():
        # contraction sharded on x -> psum: reduce_scatter+all_gather
        ea = st.from_numpy(a, tiling=tiling.row_t(2))
        eb = st.from_numpy(b, tiling=tiling.row(2))
        return st.dot(ea, eb)

    def matrix(name):
        # the {map, dot, reduce, loop} acceptance matrix, planner on
        xe = st.as_expr(a)
        if name == "map":
            return (xe + xe) * 3.0 - xe
        if name == "dot":
            return _gemm_pipeline(a, b)
        if name == "reduce":
            return (xe * xe).sum(axis=0)
        return st.loop(3, lambda c: c * 0.5 + st.as_expr(b),
                       st.as_expr(a))

    for _ in range(2):  # second run is a warm dispatch (fittable)
        psum_gemm().evaluate()
        for name in ("map", "dot", "reduce", "loop"):
            matrix(name).evaluate()
    snap = st.ledger(validate=True)
    comps = {}
    ratio_models = set()
    for entry in snap["plans"].values():
        comps.update(entry["predicted"]["cost_components"] or {})
        ratio_models |= set(entry["ratios"])
    assert {"all_gather", "all_to_all",
            "reduce_scatter"} & set(comps), comps
    # pred/actual ratios reported for the plans carrying the new
    # per-collective classes (tiling_dp scale + validated peak HBM)
    assert "tiling_dp" in ratio_models
    assert "peak_hbm" in ratio_models
    prof_fit = ledger.fit_profile()
    assert prof_fit is not None
    assert set(prof_fit.factors) & {"all_gather", "all_to_all",
                                    "reduce_scatter"}
    # the fitted profile's classes are all in the shared vocabulary
    assert set(prof_fit.factors) <= set(ledger.CLASSES)


def test_planner_with_calibration_separates_and_matches(mesh2d):
    """Planner + calibration profile compose: factors reprice the
    schedules, the fingerprint re-keys the plan, results stay
    allclose."""
    rng = np.random.RandomState(5)
    a = rng.rand(32, 32).astype(np.float32)
    FLAGS.redistribution_planner = True
    base_res = np.asarray(_gemm_pipeline(a, a).glom())
    k_plain = base.plan_signature(_gemm_pipeline(a, a))[0]
    ledger.set_profile(ledger.CalibrationProfile(
        {"all_to_all": 3.0, "all_gather": 0.5}))
    FLAGS.cost_calibration = True
    k_cal = base.plan_signature(_gemm_pipeline(a, a))[0]
    assert k_cal != k_plain
    cal_res = np.asarray(_gemm_pipeline(a, a).glom())
    np.testing.assert_allclose(cal_res, base_res, rtol=1e-4)
