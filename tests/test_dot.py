"""dot / map2 / outer / shuffle tests (SURVEY.md §4 test_dot family;
config 2 of BASELINE.json:8 in miniature)."""

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.array import tiling


@pytest.fixture(autouse=True)
def _mesh(mesh2d):
    yield


def _pair(shape, seed=0):
    x = np.random.RandomState(seed).rand(*shape).astype(np.float32)
    return x, st.from_numpy(x)


def test_dot_2d():
    a, ea = _pair((16, 8), 1)
    b, eb = _pair((8, 12), 2)
    np.testing.assert_allclose(st.dot(ea, eb).glom(), a @ b, rtol=1e-4)
    np.testing.assert_allclose((ea @ eb).glom(), a @ b, rtol=1e-4)
    np.testing.assert_allclose(ea.dot(eb).glom(), a @ b, rtol=1e-4)


def test_dot_1d_cases():
    a, ea = _pair((8,), 3)
    b, eb = _pair((8,), 4)
    np.testing.assert_allclose(st.dot(ea, eb).glom(), a @ b, rtol=1e-4)
    m, em = _pair((8, 6), 5)
    np.testing.assert_allclose(st.dot(ea, em).glom(), a @ m, rtol=1e-4)
    np.testing.assert_allclose(st.dot(em.T, ea).glom(), m.T @ a, rtol=1e-4)


def test_dot_mismatch():
    _, ea = _pair((4, 4))
    _, eb = _pair((5, 4))
    with pytest.raises(ValueError):
        st.dot(ea, eb)


def test_dot_sharded_operands():
    """Sharded x sharded: result correct whatever the input tilings."""
    a, _ = _pair((16, 16), 6)
    b, _ = _pair((16, 16), 7)
    ea = st.from_numpy(a, tiling=tiling.row(2))
    eb = st.from_numpy(b, tiling=tiling.col(2))
    out = st.dot(ea, eb)
    np.testing.assert_allclose(out.glom(), a @ b, rtol=1e-4)
    # the result is block-tiled over the mesh
    assert out.evaluate().tiling == tiling.block(2)


def test_dot_shardmap_variant():
    a, _ = _pair((16, 8), 8)
    b, _ = _pair((8, 12), 9)
    ea, eb = st.from_numpy(a), st.from_numpy(b)
    np.testing.assert_allclose(st.dot_shardmap(ea, eb).glom(), a @ b,
                               rtol=1e-4)


def test_dot_in_larger_expr():
    a, ea = _pair((8, 8), 10)
    b, eb = _pair((8, 8), 11)
    expr = (st.dot(ea, eb) + 1.0).sum()
    np.testing.assert_allclose(expr.glom(), (a @ b + 1).sum(), rtol=1e-4)


def test_outer():
    a, ea = _pair((8,), 12)
    b, eb = _pair((6,), 13)
    np.testing.assert_allclose(st.outer(ea, eb).glom(), np.outer(a, b),
                               rtol=1e-5)
    # custom combine fn
    out = st.outer(ea, eb, fn=lambda x, y: x + y).glom()
    np.testing.assert_allclose(out, a[:, None] + b[None, :], rtol=1e-5)


def test_map2_traced():
    import jax.numpy as jnp

    p, ep = _pair((16, 4), 14)
    c, ec = _pair((3, 4), 15)

    def sq_dists(points, centers):
        return ((points[:, None, :] - centers[None, :, :]) ** 2).sum(-1)

    out = st.map2([ep, ec], sq_dists).glom()
    expect = ((p[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(out, expect, rtol=1e-4)


def test_shard_map2():
    """Per-block kernel: blockwise scale with owner-computes."""
    x, ex = _pair((8, 8), 16)
    t = tiling.row(2)

    def kernel(block):
        return block * 2.0

    out = st.shard_map2([ex], kernel, in_tilings=[t], out_tiling=t,
                        out_shape=(8, 8), out_dtype=np.float32)
    np.testing.assert_allclose(out.glom(), x * 2, rtol=1e-6)


def test_shuffle_general():
    """Arbitrary redistribution: reverse tiles along axis 0 via a Python
    kernel emitting target extents (the reference's shuffle semantics)."""
    from spartan_tpu.array.extent import TileExtent

    x, _ = _pair((8, 4), 17)
    ex = st.from_numpy(x, tiling=tiling.row(2))
    n = x.shape[0]

    def rev_kernel(ext, block):
        ul = (n - ext.lr[0],) + ext.ul[1:]
        lr = (n - ext.ul[0],) + ext.lr[1:]
        yield TileExtent(ul, lr, x.shape), block[::-1]

    out = st.shuffle(ex, rev_kernel, target_shape=x.shape, combiner="set")
    np.testing.assert_array_equal(out.glom(), x[::-1])


def test_shuffle_combiner_add():
    """Overlapping emits combine with the reducer (histogram-style)."""
    from spartan_tpu.array.extent import TileExtent

    x = np.ones((8, 2), np.float32)
    ex = st.from_numpy(x, tiling=tiling.row(2))

    def to_origin(ext, block):
        yield TileExtent((0, 0), (1, 2), (1, 2)), block.sum(0, keepdims=True)

    out = st.shuffle(ex, to_origin, target_shape=(1, 2), combiner="add")
    np.testing.assert_array_equal(out.glom(), np.full((1, 2), 8.0))
