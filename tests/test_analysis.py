"""Graph sanitizer (ISSUE 2): DAG verifier, pass-invariant checker and
plan-time lints — including the mutation-kill suite: deliberately
corrupted DAGs / rewrites, each of which MUST be caught statically
with an error naming the offending node or pass."""

import importlib

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.analysis import (PassInvariantError, VerificationError,
                                  lint, verify_dag)
from spartan_tpu.array import tiling as tiling_mod
from spartan_tpu.expr.base import Expr, ExprError, ValExpr, evaluate
from spartan_tpu.utils.config import FLAGS

opt_mod = importlib.import_module("spartan_tpu.expr.optimize")


@pytest.fixture(autouse=True)
def _mesh(mesh2d):
    yield


def _arr(shape=(8, 8), seed=0):
    rng = np.random.RandomState(seed)
    return st.from_numpy(rng.rand(*shape).astype(np.float32))


@pytest.fixture()
def breaker():
    """Register a corrupted optimizer pass for one test; always
    unregisters (by object, not position — the tiling pass self-
    registers mid-run)."""
    opt_mod._ensure_tiling_pass()
    installed = []

    def install(p):
        opt_mod.register_pass(p)
        installed.append(p)
        return p

    yield install
    for p in installed:
        opt_mod._PASSES.remove(p)


# -- well-formed DAGs pass ----------------------------------------------


def test_clean_dag_checks_clean():
    e = ((st.as_expr(_arr()) + 1.0) * 2.0).sum(axis=0)
    assert verify_dag(e) == []
    assert st.check(e) == []
    assert st.check(e.optimized()) == []


def test_check_accepts_tuple_roots():
    a, b = st.as_expr(_arr(seed=1)), st.as_expr(_arr(seed=2))
    t = st.tuple_of(a + b, (a * b).sum())
    assert st.check(t) == []


# -- mutation-kill: corrupted NODES caught by st.check -------------------


def test_kill_wrong_shape_after_fusion():
    """Corrupted declared shape on a fused map node: the verifier
    re-derives the shape from the children and flags the divergence."""
    e = (st.as_expr(_arr()) + 1.0) * 2.0
    opt = e.optimized()  # map-fusion produced a fused MapExpr
    opt._shape = (7, 7)
    with pytest.raises(VerificationError, match="shape_mismatch"):
        st.check(opt)


def test_kill_dtype_drift():
    e = st.as_expr(_arr()) + 1.0
    e._dtype = np.dtype(np.int32)  # children still derive float32
    with pytest.raises(VerificationError, match="dtype_mismatch"):
        st.check(e)


def test_kill_cycle():
    e = st.as_expr(_arr()) + 1.0
    e.inputs = (e, e.inputs[1])  # self-edge
    with pytest.raises(VerificationError, match="cycle"):
        st.check(e)


def test_kill_bad_reduce_axis():
    r = st.sum(st.as_expr(_arr()), axis=0)
    r.axis = (5,)  # out of bounds for a rank-2 operand
    with pytest.raises(VerificationError, match="bad_axis"):
        st.check(r)


def test_kill_bad_transpose_perm():
    t = st.transpose(st.as_expr(_arr()))
    t.perm = (0, 5)
    with pytest.raises(VerificationError, match="bad_axis"):
        st.check(t)


def test_kill_illegal_broadcast_rewire():
    """Rewiring a map's inputs to non-broadcastable shapes is caught
    by reconstruction (the constructor IS the shape rule)."""
    e = st.as_expr(_arr((8, 8))) + st.as_expr(_arr((8, 8), seed=1))
    e.inputs = (e.inputs[0], st.as_expr(_arr((3, 5), seed=2)))
    with pytest.raises(VerificationError):
        st.check(e)


def test_kill_corrupted_slice_shape():
    s = st.as_expr(_arr())[2:6]
    s._shape = (5, 8)  # the index derives (4, 8)
    with pytest.raises(VerificationError, match="shape_mismatch"):
        st.check(s)


def test_kill_missing_sig_and_replace_children():
    class NoHooksExpr(Expr):
        def __init__(self, c):
            super().__init__(c.shape, c.dtype)
            self.c = c

        def children(self):
            return (self.c,)

    bad = NoHooksExpr(st.as_expr(_arr()))
    with pytest.raises(VerificationError) as ei:
        st.check(bad)
    assert "missing_sig" in str(ei.value)
    assert "missing_replace_children" in str(ei.value)


def test_kill_forced_tiling_rank():
    e = st.as_expr(_arr()) + 1.0
    e._forced_tiling = tiling_mod.row(3)  # rank 3 on a rank-2 node
    with pytest.raises(VerificationError, match="forced_tiling_rank"):
        st.check(e)


def test_kill_sort_tiling_out_specs_mismatch():
    """The ADVICE r5 #1 bug class: a declared/forced sort output tiling
    that diverges from the collective-axis/batch-axes the kernel's
    out_specs produce (shared helpers in ops/sort.py) is machine-caught."""
    x = st.from_numpy(np.random.RandomState(3).rand(8, 16)
                      .astype(np.float32), tiling=tiling_mod.col(2))
    srt = st.sort(x, axis=1)
    from spartan_tpu.expr.builtins import SampleSortExpr

    assert isinstance(srt, SampleSortExpr)
    assert st.check(srt) == []  # the shared-helper default is consistent
    srt._forced_tiling = tiling_mod.Tiling(("x", "y"))
    with pytest.raises(VerificationError, match="sort_tiling_mismatch"):
        st.check(srt)


# -- mutation-kill: corrupted PASSES caught by the pass checker ----------


def test_kill_pass_wrong_root_shape(breaker):
    class WrongShapePass(opt_mod.Pass):
        name = "breaker_wrong_shape"

        def run(self, root):
            return root[0:4] if root.ndim == 2 else root

    breaker(WrongShapePass())
    with pytest.raises(PassInvariantError, match="breaker_wrong_shape"):
        (st.as_expr(_arr()) + 1.0).optimized()


def test_kill_pass_dropped_leaf(breaker):
    class DropLeafPass(opt_mod.Pass):
        name = "breaker_drop_leaf"

        def run(self, root):
            # rewrite a+b -> a: leaf b silently vanishes
            return root.inputs[0] if hasattr(root, "inputs") else root

    breaker(DropLeafPass())
    a, b = st.as_expr(_arr(seed=1)), st.as_expr(_arr(seed=2))
    with pytest.raises(PassInvariantError,
                       match="breaker_drop_leaf.*dropped leaf"):
        (a + b).optimized()


def test_kill_pass_dtype_drift(breaker):
    class DtypePass(opt_mod.Pass):
        name = "breaker_dtype"

        def run(self, root):
            return st.astype(root, np.int32)

    breaker(DtypePass())
    with pytest.raises(PassInvariantError,
                       match="breaker_dtype.*dtype"):
        (st.as_expr(_arr()) * 1.5).optimized()


def test_kill_pass_corrupted_node(breaker):
    class CorruptNodePass(opt_mod.Pass):
        name = "breaker_corrupt_node"

        def run(self, root):
            root._shape = tuple(reversed((root.shape[0] + 1,)
                                         + root.shape[1:]))
            return root

    breaker(CorruptNodePass())
    with pytest.raises(PassInvariantError, match="breaker_corrupt_node"):
        (st.as_expr(_arr()) + 2.0).optimized()


def test_kill_pass_invented_leaf(breaker):
    class InventLeafPass(opt_mod.Pass):
        name = "breaker_invent_leaf"

        def run(self, root):
            fake = st.as_expr(_arr(seed=9))
            return root.replace_children(
                (root.children()[0], fake)) if len(
                    root.children()) == 2 else root

    breaker(InventLeafPass())
    with pytest.raises(PassInvariantError,
                       match="breaker_invent_leaf.*no pre-pass"):
        (st.as_expr(_arr(seed=1)) + st.as_expr(_arr(seed=2))).optimized()


def test_kill_pass_swapped_scalar_constant(breaker):
    class SwapScalarPass(opt_mod.Pass):
        name = "breaker_swap_scalar"

        def run(self, root):
            from spartan_tpu.expr.base import ScalarExpr

            if hasattr(root, "inputs") and any(
                    isinstance(i, ScalarExpr) for i in root.inputs):
                new = tuple(st.as_expr(99.0)
                            if isinstance(i, ScalarExpr) else i
                            for i in root.inputs)
                return root.replace_children(new)
            return root

    breaker(SwapScalarPass())
    with pytest.raises(PassInvariantError,
                       match="breaker_swap_scalar.*no pre-pass"):
        (st.as_expr(_arr()) * 2.5).optimized()


def test_kill_pass_introduced_cycle(breaker):
    class CyclePass(opt_mod.Pass):
        name = "breaker_cycle"

        def run(self, root):
            if hasattr(root, "inputs") and len(root.inputs) == 2:
                root.inputs = (root, root.inputs[1])
            return root

    breaker(CyclePass())
    with pytest.raises(PassInvariantError, match="cycle"):
        (st.as_expr(_arr(seed=1)) + st.as_expr(_arr(seed=2))).optimized()


def test_legit_passes_still_green_under_checker():
    """The real pass stack survives the checker on a DAG exercising
    every registered rewrite (fusion + reduce fusion + collapse +
    tiling)."""
    assert FLAGS.verify_passes  # pytest default (conftest)
    a = st.as_expr(_arr(seed=4))
    inner = (a * 2.0 + 1.0)
    inner_val = ValExpr(inner.evaluate())
    out = ((inner_val + a) * (a - 0.5)).sum(axis=1)
    got = np.asarray(out.optimized().glom())
    an = np.asarray(a.glom())
    ref = ((an * 2.0 + 1.0 + an) * (an - 0.5)).sum(axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


# -- plan-time donation lints -------------------------------------------


def test_check_use_after_donate_has_provenance():
    x = st.from_numpy(np.random.RandomState(5).rand(8, 8)
                      .astype(np.float32)).evaluate()
    stale = st.as_expr(x) * 2.0       # built BEFORE the donation
    evaluate(st.as_expr(x) + 1.0, donate=[x])
    assert x.is_donated
    with pytest.raises(VerificationError, match="use_after_donate"):
        st.check(stale)
    # provenance: the donating call's user site is in the message
    with pytest.raises(VerificationError, match="test_analysis"):
        st.check(stale)


def test_check_double_donation():
    y = st.from_numpy(np.random.RandomState(6).rand(8, 8)
                      .astype(np.float32)).evaluate()
    y.donate()
    e = ValExpr(y) + ValExpr(y) * 2.0  # one buffer, two leaf slots
    with pytest.raises(VerificationError, match="double_donation"):
        st.check(e)


def test_check_double_donation_in_donate_list():
    y = st.from_numpy(np.random.RandomState(7).rand(8, 8)
                      .astype(np.float32)).evaluate()
    e = st.as_expr(y) + 1.0
    with pytest.raises(VerificationError, match="double_donation"):
        st.check(e, donate=[y, y])


def test_lint_donation_unused_is_warning():
    y = st.from_numpy(np.ones((4, 4), np.float32)).evaluate()
    z = st.from_numpy(np.ones((4, 4), np.float32)).evaluate()
    e = st.as_expr(y) + 1.0
    findings = lint(e, donate=[z])
    assert any(f.kind == "donation_unused" and f.severity == "warning"
               for f in findings)
    # check() reports but does not raise on warnings
    assert any(f.kind == "donation_unused"
               for f in st.check(e, donate=[z]))


def test_verify_evaluate_flag_raises_on_miss_path():
    x = st.from_numpy(np.random.RandomState(8).rand(8, 8)
                      .astype(np.float32)).evaluate()
    bad = st.as_expr(x) - 1.0         # built BEFORE the donation
    evaluate(st.as_expr(x) * 3.0, donate=[x])
    try:
        FLAGS.verify_evaluate = True
        with pytest.raises(VerificationError, match="use_after_donate"):
            bad.evaluate()
    finally:
        FLAGS.reset_all()


def test_donation_caught_on_cached_plan_hit_path_with_provenance():
    """A donated leaf feeding a CACHED plan (hit path — no optimizer,
    no verifier) still raises before dispatch, with the donating
    call's provenance in the message."""
    st.clear_compile_cache()
    xn = np.random.RandomState(9).rand(8, 8).astype(np.float32)
    x = st.from_numpy(xn).evaluate()
    stale = st.as_expr(x) + 1.0               # built BEFORE the donation
    (st.as_expr(x) + 1.0).evaluate()          # plan MISS: compile + cache
    evaluate(st.as_expr(x) + 1.0, donate=[x])  # plan HIT: donates x
    assert x.is_donated
    from spartan_tpu.utils import profiling

    profiling.reset_counters()
    with pytest.raises(RuntimeError, match="donated at"):
        stale.evaluate()                      # HIT again: dead buffer
    assert profiling.counters().get("plan_hits", 0) == 1  # really the hit path
    with pytest.raises(RuntimeError, match="test_analysis"):
        stale.evaluate()


# -- tiling lints --------------------------------------------------------


def test_lint_degenerate_tile_warning():
    x = st.from_numpy(np.ones((2, 8), np.float32),
                      tiling=tiling_mod.replicated(2))
    e = st.as_expr(x) + 1.0
    e._forced_tiling = tiling_mod.row(2)  # 2 rows split 4 ways
    findings = lint(e)
    assert any(f.kind == "degenerate_tile" for f in findings)


def test_lint_unresolvable_tiling_warning():
    x = st.from_numpy(np.ones((8, 8), np.float32))
    e = st.as_expr(x) + 1.0
    e._forced_tiling = tiling_mod.Tiling(("nope", None))
    findings = lint(e)
    assert any(f.kind == "unresolvable_tiling" for f in findings)


def test_seeded_tiling_rank_corruption_caught_by_lint_layer():
    """Mutation coverage for the LINT layer's rank check (kind
    ``tiling_rank``), distinct from the verifier's
    ``forced_tiling_rank``: seed a wrong-rank forced tiling and assert
    the non-raising ``lint()`` entry point reports it as an error
    attributed to the corrupted node — deleting the rank check in
    ``tiling_findings`` fails THIS test even while ``st.check`` still
    raises via the verifier."""
    e = st.as_expr(_arr()) + 1.0
    e._forced_tiling = tiling_mod.row(3)  # rank 3 on a rank-2 node
    findings = lint(e)
    hits = [f for f in findings if f.kind == "tiling_rank"]
    assert hits and all(f.severity == "error" for f in hits)
    assert any("rank 3" in f.message and "rank 2" in f.message
               for f in hits)


def test_seeded_use_after_donate_caught_by_lint_layer():
    """Mutation coverage for ``donation_findings``: donate through a
    REAL dispatch (not a hand-set flag), then reuse the dead handle —
    the non-raising ``lint()`` entry point must surface the
    ``use_after_donate`` error with the donating call's provenance,
    independent of ``st.check``'s raise path."""
    x = st.from_numpy(np.random.RandomState(11).rand(8, 8)
                      .astype(np.float32)).evaluate()
    stale = st.as_expr(x) * 3.0       # built BEFORE the donation
    evaluate(st.as_expr(x) + 1.0, donate=[x])
    assert x.is_donated
    findings = lint(stale)
    hits = [f for f in findings if f.kind == "use_after_donate"]
    assert hits and all(f.severity == "error" for f in hits)
    # provenance: the donating call's user site is in the message
    assert any("test_analysis" in f.message for f in hits)


# -- Expr.__bool__ satellite --------------------------------------------


def test_bool_raises_expr_error_with_site():
    e = st.as_expr(_arr()) + 1.0
    with pytest.raises(ExprError, match="truth value|truth-tested"):
        bool(e)
    with pytest.raises(ExprError, match="test_analysis"):
        if e:  # the classic silent-graph-build footgun
            pass


def test_expr_in_list_raises_loudly():
    e = st.as_expr(_arr())
    f = st.as_expr(_arr(seed=1))
    with pytest.raises(ExprError):
        e in [f]  # __eq__ builds a lazy graph; bool() must refuse
    # identity membership is the supported spelling
    assert any(x is e for x in [f, e])


def test_size_one_bool_also_raises():
    """Even size-1 exprs refuse truth-testing (it silently forced a
    whole evaluation pre-ISSUE-2); bool(expr.glom()) is the spelling."""
    s = st.sum(st.as_expr(_arr()))
    with pytest.raises(ExprError):
        bool(s)
    assert bool(s.glom() > 0)
