"""Masked distributed arrays vs the numpy.ma oracle (SURVEY.md §2.2:
reference tiles support masked arrays; §4: NumPy is the universal
oracle)."""

import numpy as np
import numpy.ma as ma
import pytest

import spartan_tpu as st
from spartan_tpu.array.masked import MaskedDistArray


@pytest.fixture
def pair():
    rng = np.random.RandomState(0)
    data = rng.rand(12, 10).astype(np.float32) + 0.5
    mask = rng.rand(12, 10) < 0.3
    return ma.masked_array(data, mask), MaskedDistArray.from_numpy(
        ma.masked_array(data, mask))


def _eq(nma, sma, rtol=1e-5):
    got = sma.glom() if isinstance(sma, MaskedDistArray) else sma
    if isinstance(got, ma.MaskedArray):
        np.testing.assert_array_equal(ma.getmaskarray(got),
                                      ma.getmaskarray(nma))
        np.testing.assert_allclose(got.filled(0), nma.filled(0), rtol=rtol)
    else:
        np.testing.assert_allclose(np.asarray(got), np.asarray(nma),
                                   rtol=rtol)


def test_roundtrip(pair):
    nma, sma = pair
    _eq(nma, sma)


def test_arithmetic_mask_union(pair):
    nma, sma = pair
    rng = np.random.RandomState(1)
    d2 = rng.rand(12, 10).astype(np.float32) + 0.5
    m2 = rng.rand(12, 10) < 0.2
    nmb = ma.masked_array(d2, m2)
    smb = MaskedDistArray.from_numpy(nmb)
    _eq(nma + nmb, sma + smb)
    _eq(nma * nmb, sma * smb)
    _eq(nma - nmb, sma - smb)
    _eq(nma / nmb, sma / smb)
    _eq(nma + 2.0, sma + 2.0)
    _eq(3.0 * nma, 3.0 * sma)
    _eq(-nma, -sma)


def test_reductions(pair):
    nma, sma = pair
    _eq(nma.sum(), float(sma.sum().glom()))
    _eq(nma.sum(axis=0), sma.sum(axis=0).glom())
    _eq(nma.sum(axis=1), sma.sum(axis=1).glom())
    _eq(nma.mean(), float(sma.mean().glom()))
    _eq(nma.mean(axis=1), sma.mean(axis=1).glom())
    _eq(nma.max(), float(sma.max().glom()))
    _eq(nma.min(axis=0), sma.min(axis=0).glom())
    assert int(sma.count().glom()) == nma.count()
    np.testing.assert_array_equal(np.asarray(sma.count(axis=1).glom()),
                                  nma.count(axis=1))


def test_var_std(pair):
    nma, sma = pair
    np.testing.assert_allclose(float(sma.var().glom()), nma.var(),
                               rtol=1e-4)
    np.testing.assert_allclose(float(sma.std().glom()), nma.std(),
                               rtol=1e-4)


def test_filled(pair):
    nma, sma = pair
    np.testing.assert_allclose(np.asarray(sma.filled(7.0).glom()),
                               nma.filled(7.0), rtol=1e-6)


def test_masked_invalid():
    data = np.array([[1.0, np.nan], [np.inf, 4.0]], np.float32)
    sma = MaskedDistArray.masked_invalid(st.from_numpy(data))
    nma = ma.masked_invalid(data)
    _eq(nma, sma)
    assert float(sma.sum().glom()) == 5.0


def test_masked_where():
    data = np.arange(12, dtype=np.float32).reshape(3, 4)
    sma = MaskedDistArray.masked_where(st.from_numpy(data) > 6.0,
                                       st.from_numpy(data))
    nma = ma.masked_where(data > 6.0, data)
    _eq(nma, sma)
    _eq(nma.sum(), float(sma.sum().glom()))


def test_evaluate_one_program():
    from spartan_tpu.expr import base

    data = np.arange(6, dtype=np.float32).reshape(2, 3)
    mask = data > 3
    sma = MaskedDistArray.from_numpy(ma.masked_array(data, mask))
    base.clear_compile_cache()
    out = (sma + 1.0).evaluate()
    assert base.compile_cache_size() == 1
    _eq(ma.masked_array(data, mask) + 1.0, out)


def test_fully_masked_slice_max():
    data = np.arange(12, dtype=np.float32).reshape(3, 4)
    mask = np.zeros((3, 4), bool)
    mask[1, :] = True  # row 1 fully masked
    nma = ma.masked_array(data, mask)
    sma = MaskedDistArray.from_numpy(nma)
    got = sma.max(axis=1).glom()
    want = nma.max(axis=1)
    np.testing.assert_array_equal(ma.getmaskarray(got),
                                  ma.getmaskarray(want))
    np.testing.assert_allclose(got.filled(0), want.filled(0))
    got_min = sma.min(axis=1).glom()
    want_min = nma.min(axis=1)
    np.testing.assert_array_equal(ma.getmaskarray(got_min),
                                  ma.getmaskarray(want_min))


def test_force_second_carry_first():
    """Forcing the SECOND item of a multi-carry loop first must work
    (identity containment, not Expr.__eq__)."""
    ea = st.from_numpy(np.ones((4, 4), np.float32))
    eb = st.from_numpy(np.full((4, 4), 2.0, np.float32))
    fa, fb = st.loop(3, lambda a, b: (b, a + b), ea, eb)
    gb = fb.glom()
    a, b = np.ones((4, 4)), np.full((4, 4), 2.0)
    for _ in range(3):
        a, b = b, a + b
    np.testing.assert_allclose(gb, b)
    np.testing.assert_allclose(fa.glom(), a)


def test_bool_masked_max_min():
    """Regression (ADVICE r1): masked bool max() must not leak a
    masked-out True; fill identities are False for max, True for min."""
    data = np.array([False, True, False])
    mask = np.array([False, True, False])
    nma = ma.masked_array(data, mask)
    sma = MaskedDistArray.from_numpy(nma)
    assert bool(sma.max().glom()) == bool(nma.max())  # False
    assert bool(sma.min().glom()) == bool(nma.min())  # False
    # and the dual: masked-out False must not leak into min()
    nmb = ma.masked_array(np.array([True, False, True]),
                          np.array([False, True, False]))
    smb = MaskedDistArray.from_numpy(nmb)
    assert bool(smb.min().glom()) == bool(nmb.min())  # True
    assert bool(smb.max().glom()) == bool(nmb.max())  # True


def test_var_std_per_axis(pair):
    """Round-3 verdict Missing #5: per-axis masked var/std vs numpy.ma
    (valid slices exact; fully-masked slices NaN where ma masks)."""
    nma, sma = pair
    for axis in (0, 1):
        for ours_e, ref_ma in ((sma.var(axis), nma.var(axis)),
                               (sma.std(axis), nma.std(axis))):
            ours = np.asarray(ours_e.glom())
            ref = np.ma.filled(ref_ma.astype(np.float64), np.nan)
            np.testing.assert_allclose(ours, ref, rtol=1e-4,
                                       equal_nan=True)


def test_var_std_fully_masked_slice(mesh2d):
    """A fully-masked column: its per-axis var is NaN (the masked
    result), other columns stay exact."""
    rng = np.random.RandomState(9)
    data = rng.rand(8, 4).astype(np.float32)
    mask = np.zeros((8, 4), bool)
    mask[:, 2] = True  # column 2 fully masked
    mask[0, 0] = True  # partial masking elsewhere
    sma = MaskedDistArray(data, mask)
    nma = np.ma.masked_array(data, mask)
    got = np.asarray(sma.var(axis=0).glom())
    ref = np.ma.filled(nma.var(axis=0).astype(np.float64), np.nan)
    np.testing.assert_allclose(got, ref, rtol=1e-4, equal_nan=True)
    assert np.isnan(got[2])
    got_std = np.asarray(sma.std(axis=1).glom())
    ref_std = np.ma.filled(nma.std(axis=1).astype(np.float64), np.nan)
    np.testing.assert_allclose(got_std, ref_std, rtol=1e-4,
                               equal_nan=True)


def test_average_weighted(pair):
    nma, sma = pair
    w = np.linspace(1.0, 2.0, nma.size).reshape(nma.shape).astype(
        np.float32)
    np.testing.assert_allclose(
        float(sma.average(weights=w).glom()),
        np.ma.average(nma, weights=w), rtol=1e-5)
    for axis in (0, 1):
        got = np.asarray(sma.average(axis=axis, weights=w).glom())
        ref = np.ma.filled(
            np.ma.average(nma, axis=axis, weights=w).astype(np.float64),
            np.nan)
        np.testing.assert_allclose(got, ref, rtol=1e-4, equal_nan=True)
    # numpy.ma's 1-D per-axis weights form
    w0 = np.linspace(0.5, 1.5, nma.shape[0]).astype(np.float32)
    got = np.asarray(sma.average(axis=0, weights=w0).glom())
    ref = np.ma.filled(
        np.ma.average(nma, axis=0, weights=w0).astype(np.float64), np.nan)
    np.testing.assert_allclose(got, ref, rtol=1e-4, equal_nan=True)


def test_anom(pair):
    nma, sma = pair
    for axis in (None, 0, 1):
        got = sma.anom(axis=axis).glom()
        ref = nma.anom(axis=axis)
        np.testing.assert_allclose(
            np.ma.filled(got.astype(np.float64), np.nan),
            np.ma.filled(ref.astype(np.float64), np.nan),
            rtol=1e-4, atol=1e-6, equal_nan=True)


def test_compressed(pair):
    nma, sma = pair
    np.testing.assert_allclose(sma.compressed(), nma.compressed(),
                               rtol=1e-6)


def test_mean_keepdims_consistency(pair):
    """keepdims changes shape only — never values (fully-masked slices
    NaN either way); axis=None keepdims keeps all-ones shape."""
    nma, sma = pair
    k = np.asarray(sma.mean(axis=0, keepdims=True).glom())
    f = np.asarray(sma.mean(axis=0).glom())
    assert k.shape == (1, nma.shape[1])
    np.testing.assert_allclose(k[0], f, rtol=1e-6, equal_nan=True)
    assert np.asarray(sma.mean(keepdims=True).glom()).shape == (1, 1)
    # fully-masked column: NaN under BOTH keepdims settings
    mask = np.zeros((4, 3), bool)
    mask[:, 1] = True
    m2 = MaskedDistArray(np.ones((4, 3), np.float32), mask)
    assert np.isnan(np.asarray(m2.mean(axis=0).glom())[1])
    assert np.isnan(np.asarray(m2.mean(axis=0, keepdims=True).glom())[0, 1])


def test_average_rejects_bad_weights(pair):
    nma, sma = pair
    bad = np.ones(nma.shape[1] + 1, np.float32)
    with pytest.raises(ValueError, match="not compatible"):
        sma.average(axis=1, weights=bad)
    with pytest.raises(TypeError, match="Axis must be specified"):
        sma.average(weights=np.ones(nma.shape[0], np.float32))
    # 1-D data with wrong-length 1-D weights: caught up front, not as
    # an opaque trace-time broadcast error (round-4 advisor, low)
    d1 = MaskedDistArray(np.arange(6, dtype=np.float32),
                         np.zeros(6, bool))
    with pytest.raises(ValueError, match="not compatible"):
        d1.average(weights=np.ones(4, np.float32))


# -- mask-aware general ops (round-4 verdict Missing #3) ----------------


def _ma_pair(shape, frac=0.3, seed=31):
    rng = np.random.RandomState(seed)
    data = rng.rand(*shape).astype(np.float32)
    mask = rng.rand(*shape) < frac
    return (np.ma.masked_array(data, mask),
            MaskedDistArray(data, mask))


def test_masked_dot_oracle(mesh2d):
    nma, sma = _ma_pair((24, 16), seed=41)
    nmb, smb = _ma_pair((16, 20), seed=42)
    got = st.dot(sma, smb).glom()
    ref = np.ma.dot(nma, nmb)
    np.testing.assert_allclose(np.ma.filled(got.astype(np.float64), 0),
                               np.ma.filled(ref.astype(np.float64), 0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.ma.getmaskarray(got),
                                  np.ma.getmaskarray(ref))
    # mixed masked x plain
    b = np.asarray(nmb.data)
    got2 = st.dot(sma, b).glom()
    ref2 = np.ma.dot(nma, b)
    np.testing.assert_allclose(np.ma.filled(got2.astype(np.float64), 0),
                               np.ma.filled(ref2.astype(np.float64), 0),
                               rtol=1e-4, atol=1e-5)


def test_masked_dot_fully_masked_cell(mesh2d):
    """A result cell with NO valid (a, b) pair is masked, like
    np.ma.dot."""
    a = np.ma.masked_array(np.ones((2, 3), np.float32),
                           [[True, True, True], [False, False, False]])
    b = np.ones((3, 2), np.float32)
    sa = MaskedDistArray(np.asarray(a.data), np.ma.getmaskarray(a))
    got = st.dot(sa, b).glom()
    ref = np.ma.dot(a, b)
    np.testing.assert_array_equal(np.ma.getmaskarray(got),
                                  np.ma.getmaskarray(ref))
    assert np.ma.getmaskarray(got)[0].all()
    np.testing.assert_allclose(np.ma.filled(got, 0),
                               np.ma.filled(ref, 0), rtol=1e-6)


def test_masked_sort_and_argsort(mesh2d):
    nma, sma = _ma_pair((8, 12), seed=43)
    for axis in (0, 1, -1):
        got = st.sort(sma, axis=axis).glom()
        ref = np.ma.sort(nma, axis=axis)
        np.testing.assert_array_equal(np.ma.getmaskarray(got),
                                      np.ma.getmaskarray(ref))
        np.testing.assert_allclose(
            np.ma.filled(got.astype(np.float64), -1),
            np.ma.filled(ref.astype(np.float64), -1), rtol=1e-6)
    perm = np.asarray(st.argsort(sma, axis=1).glom())
    # valid elements ordered first, ascending
    dat = np.asarray(nma.data)
    msk = np.ma.getmaskarray(nma)
    for r in range(8):
        k = int((~msk[r]).sum())
        vals = dat[r][perm[r][:k]]
        assert not msk[r][perm[r][:k]].any()
        assert np.all(np.diff(vals) >= 0)


def test_masked_median_oracle(mesh1d):
    nma, sma = _ma_pair((64,), seed=44)
    got = float(st.median(sma).glom())
    np.testing.assert_allclose(got, np.ma.median(nma), rtol=1e-6)
    nmb, smb = _ma_pair((6, 10), seed=45)
    got2 = np.asarray(st.median(smb, axis=1).glom())
    ref2 = np.ma.filled(np.ma.median(nmb, axis=1).astype(np.float64),
                        np.nan)
    np.testing.assert_allclose(got2, ref2, rtol=1e-5, equal_nan=True)
    # fully-masked row: NaN (the Expr-level masked result)
    full = MaskedDistArray(np.ones((2, 4), np.float32),
                           np.array([[True] * 4, [False] * 4]))
    out = np.asarray(st.median(full, axis=1).glom())
    assert np.isnan(out[0]) and out[1] == 1.0
    # a genuine NaN in a VALID slot poisons (numpy.ma does not treat
    # NaN as missing) — but a NaN in a MASKED slot does not
    d = np.array([[1.0, np.nan, 3.0], [1.0, np.nan, 3.0]], np.float32)
    mk = np.array([[False, False, True], [False, True, False]])
    mm = MaskedDistArray(d, mk)
    out2 = np.asarray(st.median(mm, axis=1).glom())
    assert np.isnan(out2[0])        # valid NaN -> NaN
    assert out2[1] == 2.0           # masked NaN skipped: median(1, 3)


def test_masked_sort_axis_out_of_range(mesh1d):
    _, sma = _ma_pair((4, 4), seed=51)
    with pytest.raises(ValueError, match="out of range"):
        st.sort(sma, axis=2)
    with pytest.raises(ValueError, match="out of range"):
        st.argsort(sma, axis=-3)


def test_masked_concatenate(mesh1d):
    nma, sma = _ma_pair((5, 4), seed=46)
    nmb, smb = _ma_pair((3, 4), seed=47)
    got = st.concatenate([sma, smb], axis=0).glom()
    ref = np.ma.concatenate([nma, nmb], axis=0)
    np.testing.assert_array_equal(np.ma.getmaskarray(got),
                                  np.ma.getmaskarray(ref))
    np.testing.assert_allclose(np.ma.filled(got, 9), np.ma.filled(ref, 9),
                               rtol=1e-6)
    # plain operand contributes an all-False mask
    plain = np.ones((2, 4), np.float32)
    got2 = st.concatenate([sma, plain], axis=0).glom()
    assert not np.ma.getmaskarray(got2)[5:].any()


def test_masked_map_expr_propagates(mesh1d):
    from spartan_tpu.expr.map import map as map_expr

    nma, sma = _ma_pair((16,), seed=48)
    nmb, smb = _ma_pair((16,), seed=49)
    got = map_expr(lambda a, b: a * 2.0 + b, sma, smb)
    assert isinstance(got, MaskedDistArray)
    ref_mask = np.ma.getmaskarray(nma) | np.ma.getmaskarray(nmb)
    g = got.glom()
    np.testing.assert_array_equal(np.ma.getmaskarray(g), ref_mask)
    np.testing.assert_allclose(
        np.asarray(g.data)[~ref_mask],
        (np.asarray(nma.data) * 2.0 + np.asarray(nmb.data))[~ref_mask],
        rtol=1e-6)


def test_masked_unsupported_op_raises(mesh1d):
    """An op without a mask-aware path refuses the masked operand with
    a clear message instead of silently dropping the mask."""
    _, sma = _ma_pair((16,), seed=50)
    with pytest.raises(TypeError, match="MaskedDistArray"):
        st.cumsum(sma)
    with pytest.raises(TypeError, match="mask-aware"):
        st.einsum("i,i->", sma, sma)
