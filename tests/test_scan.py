"""Distributed blocked prefix scan vs NumPy oracle (SURVEY.md §2.3
scan; BASELINE.json:11). The traced jnp.cumsum alternative all-gathers
a sharded scan axis (and measured minutes at 4M elements on the CPU
mesh), so axis-0 scans must route to the blocked shard_map program."""

import numpy as np

import spartan_tpu as st
from spartan_tpu.array import tiling
from spartan_tpu.expr.builtins import BlockedScanExpr


def test_blocked_cumsum_1d(mesh1d):
    rng = np.random.RandomState(0)
    a = rng.rand(1 << 20).astype(np.float32)
    e = st.cumsum(st.from_numpy(a, tiling=tiling.row(1)))
    assert isinstance(e, BlockedScanExpr)
    np.testing.assert_allclose(np.asarray(e.glom()), np.cumsum(a),
                               rtol=1e-4)


def test_blocked_scan_2d_axis0(mesh1d):
    rng = np.random.RandomState(1)
    a = rng.rand(4096, 8).astype(np.float32)
    e = st.scan(st.from_numpy(a, tiling=tiling.row(2)), axis=0)
    assert isinstance(e, BlockedScanExpr)
    np.testing.assert_allclose(np.asarray(e.glom()),
                               np.cumsum(a, axis=0), rtol=1e-4)


def test_blocked_scan_ops(mesh2d):
    rng = np.random.RandomState(2)
    a = (0.9 + 0.2 * rng.rand(8192)).astype(np.float32)  # mul-safe
    fa = st.from_numpy(a, tiling=tiling.row(1))
    np.testing.assert_allclose(
        np.asarray(st.scan(fa, op="mul").glom()), np.cumprod(a),
        rtol=1e-3)
    b = rng.randn(8192).astype(np.float32)
    fb = st.from_numpy(b, tiling=tiling.row(1))
    np.testing.assert_array_equal(
        np.asarray(st.scan(fb, op="max").glom()),
        np.maximum.accumulate(b))
    np.testing.assert_array_equal(
        np.asarray(st.scan(fb, op="min").glom()),
        np.minimum.accumulate(b))


def test_blocked_scan_int_max(mesh1d):
    rng = np.random.RandomState(3)
    a = rng.randint(-100, 100, size=4096).astype(np.int32)
    e = st.scan(st.from_numpy(a, tiling=tiling.row(1)), op="max")
    assert isinstance(e, BlockedScanExpr)
    np.testing.assert_array_equal(np.asarray(e.glom()),
                                  np.maximum.accumulate(a))


def test_scan_output_stays_sharded(mesh1d):
    rng = np.random.RandomState(4)
    a = rng.rand(8192).astype(np.float32)
    out = st.cumsum(st.from_numpy(a, tiling=tiling.row(1))).evaluate()
    shards = out.jax_array.addressable_shards
    assert len({s.device for s in shards}) == 8
    assert all(s.data.shape == (1024,) for s in shards)


def test_scan_fallback_non_divisible(mesh1d):
    rng = np.random.RandomState(5)
    a = rng.rand(1001).astype(np.float32)
    e = st.cumsum(st.from_numpy(a))
    assert not isinstance(e, BlockedScanExpr)
    np.testing.assert_allclose(np.asarray(e.glom()), np.cumsum(a),
                               rtol=1e-4)


def test_scan_axis1_stays_local(mesh1d):
    rng = np.random.RandomState(6)
    a = rng.rand(64, 16).astype(np.float32)
    e = st.scan(st.from_numpy(a, tiling=tiling.row(2)), axis=1)
    assert not isinstance(e, BlockedScanExpr)
    np.testing.assert_allclose(np.asarray(e.glom()),
                               np.cumsum(a, axis=1), rtol=1e-4)


def test_scan_bool_promotes_via_local_path(mesh1d):
    """bool cumsum promotes to int32 — must take the dtype-inferring
    map path, not the blocked dispatch."""
    mask = (np.arange(4096) % 3 == 0)
    e = st.cumsum(st.from_numpy(mask))
    assert not isinstance(e, BlockedScanExpr)
    got = np.asarray(e.glom())
    np.testing.assert_array_equal(got, np.cumsum(mask))


def test_scan_col_sharded_stays_local(mesh2d):
    """Axis 0 unsharded + axis 1 sharded: the local per-shard scan is
    collective-free; the blocked dispatch must not force a reshard."""
    rng = np.random.RandomState(7)
    a = rng.rand(64, 16).astype(np.float32)
    e = st.scan(st.from_numpy(a, tiling=tiling.col(2)), axis=0)
    assert not isinstance(e, BlockedScanExpr)
    np.testing.assert_allclose(np.asarray(e.glom()),
                               np.cumsum(a, axis=0), rtol=1e-4)


def test_scan_block_tiled_keeps_column_sharding(mesh2d):
    """A block-tiled operand keeps its column sharding through the
    blocked scan — no all-gather of the trailing axis."""
    rng = np.random.RandomState(8)
    a = rng.rand(64, 8).astype(np.float32)
    e = st.scan(st.from_numpy(a, tiling=tiling.block(2)), axis=0)
    assert isinstance(e, BlockedScanExpr)
    assert e.out_tiling().axes == ("x", "y")
    out = e.evaluate()
    np.testing.assert_allclose(np.asarray(out.glom()),
                               np.cumsum(a, axis=0), rtol=1e-4)
    # result shards stay 2-D block partitioned over all 8 devices
    shards = out.jax_array.addressable_shards
    assert len({s.device for s in shards}) == 8
    assert all(s.data.shape == (16, 4) for s in shards)
