"""Auxiliary subsystem tests: profiling/cost analysis, error
attribution, lineage recompute, sort/stencil ops."""

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.utils import profiling
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _mesh(mesh2d):
    yield


def test_cost_analysis_reports_flops():
    a = st.from_numpy(np.ones((32, 32), np.float32))
    b = st.from_numpy(np.ones((32, 32), np.float32))
    stats = profiling.cost_analysis(st.dot(a, b))
    # reported per partition: global 2*n^3 spread over the 8 devices
    assert stats.get("flops", 0) >= 2 * 32 * 32 * 32 / 8


def test_benchmark_harness():
    x = st.from_numpy(np.ones((8, 8), np.float32))
    res = profiling.benchmark(lambda: (x + 1.0).glom(), iters=3)
    assert res["best"] > 0 and res["iters"] == 3


def test_error_attribution():
    """Errors surfacing at force-time (not construction) are annotated
    with the user line that built the failing expr. ShardMap2Expr defers
    kernel tracing to lowering, so the failure happens inside evaluate."""
    import jax.numpy as jnp

    from spartan_tpu.array import tiling

    x = st.from_numpy(np.ones((8, 8), np.float32))
    t = tiling.row(2)
    bad = st.shard_map2([x], lambda v: jnp.broken_fn(v), [t], t,  # noqa
                        (8, 8), np.float32)
    with pytest.raises(Exception) as exc_info:
        bad.glom()
    notes = getattr(exc_info.value, "__notes__", [])
    assert any("test_aux.py" in n for n in notes), notes


def test_lineage_recompute():
    x = st.from_numpy(np.ones((8, 8), np.float32))
    e = (x * 3.0).sum()
    first = e.glom()
    assert e._result is not None
    e.invalidate()
    assert e._result is None
    second = e.recompute().glom()
    np.testing.assert_array_equal(first, second)


def test_determinism_check_flag():
    FLAGS.check_determinism = True
    try:
        x = st.from_numpy(np.ones((8, 8), np.float32))
        out = (x + x).glom()
        np.testing.assert_array_equal(out, np.full((8, 8), 2.0))
    finally:
        FLAGS.check_determinism = False


def test_sort_argsort_median():
    rng = np.random.RandomState(0)
    x = rng.rand(8, 16).astype(np.float32)
    ex = st.from_numpy(x)
    np.testing.assert_array_equal(st.sort(ex).glom(), np.sort(x, axis=-1))
    np.testing.assert_array_equal(st.sort(ex, axis=0).glom(),
                                  np.sort(x, axis=0))
    np.testing.assert_array_equal(st.argsort(ex).glom(),
                                  np.argsort(x, axis=-1))
    np.testing.assert_allclose(st.median(ex).glom(), np.median(x),
                               rtol=1e-6)


def test_stencil_and_pooling():
    from spartan_tpu.ops.stencil import avgpool, maxpool, stencil

    rng = np.random.RandomState(1)
    img = rng.rand(2, 8, 8, 3).astype(np.float32)
    filt = rng.rand(3, 3, 3, 4).astype(np.float32)
    out = stencil(img, filt, stride=1, padding="SAME").glom()
    assert out.shape == (2, 8, 8, 4)
    # oracle via scipy-style direct computation on one pixel
    patch = img[0, 0:3, 0:3, :]
    np.testing.assert_allclose(out[0, 1, 1, 0],
                               (patch * filt[..., 0]).sum(), rtol=1e-4)
    mp = maxpool(img, 2).glom()
    assert mp.shape == (2, 4, 4, 3)
    np.testing.assert_allclose(mp[0, 0, 0, 0], img[0, :2, :2, 0].max())
    ap = avgpool(img, 2).glom()
    np.testing.assert_allclose(ap[0, 0, 0, 0], img[0, :2, :2, 0].mean(),
                               rtol=1e-5)


def test_device_memory_stats_shape():
    stats = profiling.device_memory_stats()
    assert isinstance(stats, dict)


def test_fault_injection_lineage_recovery():
    """SURVEY.md §5 failure recovery, migrated to the resilience
    injection API (PR 5): a TRANSIENT execution fault (the analogue
    of a lost worker/tile) is injected at the real dispatch seam by
    ``st.chaos`` and retried by the in-evaluate policy engine —
    exprs are deterministic, so the DAG is the recovery log and a
    plain ``evaluate()`` recovers by itself."""
    from spartan_tpu.utils.config import FLAGS

    x = st.from_numpy(np.arange(64, dtype=np.float32).reshape(8, 8))
    e = (x * 2.0 + 1.0).sum(axis=0)
    expected = (np.arange(64, dtype=np.float32).reshape(8, 8)
                * 2.0 + 1.0).sum(axis=0)

    before = st.metrics()["counters"].get("resilience_retries", 0)
    saved = FLAGS.retry_backoff_s
    FLAGS.retry_backoff_s = 0.0
    try:
        with st.chaos("transient@0x2") as plan:  # two failed dispatches
            out = e.evaluate()
    finally:
        FLAGS.retry_backoff_s = saved
    assert [f["kind"] for f in plan.fired] == ["transient", "transient"]
    after = st.metrics()["counters"].get("resilience_retries", 0)
    assert after - before == 2  # attempt 1+2 faulted, attempt 3 ran
    np.testing.assert_allclose(np.asarray(out.glom()), expected,
                               rtol=1e-6)


def test_evaluate_with_recovery_api(monkeypatch):
    """The legacy driver-level loop (utils/recovery.py) survives as a
    DEPRECATED shim over resilience.engine.retry_evaluate: transient
    faults retry from lineage, and — the classifier routing — user
    errors propagate immediately even though they are RuntimeError
    siblings under the old blind default."""
    from spartan_tpu.utils.recovery import evaluate_with_recovery

    x = st.from_numpy(np.full((4, 4), 2.0, np.float32))
    e = (x * x).sum()

    calls = {"n": 0, "hook": []}
    real = type(e).evaluate

    def flaky(self):
        calls["n"] += 1
        if calls["n"] <= 2:  # a transient-classified status message
            raise RuntimeError("UNAVAILABLE: injected device loss")
        return real(self)

    monkeypatch.setattr(type(e), "evaluate", flaky)
    with pytest.warns(DeprecationWarning, match="policy engine"):
        out = evaluate_with_recovery(
            e, retries=3,
            on_failure=lambda a, exc: calls["hook"].append(a))
    monkeypatch.undo()
    assert calls["n"] == 3 and calls["hook"] == [0, 1]
    np.testing.assert_allclose(np.asarray(out.glom()), 64.0)

    # a user error is NOT retried...
    bad = st.from_numpy(np.ones((4, 4), np.float32))
    b = (bad * 1.0).sum()

    def user_error(self):
        calls["n"] += 100
        raise ValueError("user bug")

    monkeypatch.setattr(type(b), "evaluate", user_error)
    before = calls["n"]
    with pytest.warns(DeprecationWarning):
        with pytest.raises(ValueError):
            evaluate_with_recovery(b, retries=3)
    monkeypatch.undo()
    assert calls["n"] == before + 100  # exactly one attempt

    # ... and neither is a DETERMINISTIC RuntimeError under the
    # classifier default (the old shim would have retried it)
    c = (bad * 2.0).sum()

    def compile_error(self):
        calls["n"] += 1000
        raise RuntimeError("INVALID_ARGUMENT: bad layout")

    monkeypatch.setattr(type(c), "evaluate", compile_error)
    before = calls["n"]
    with pytest.warns(DeprecationWarning):
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
            evaluate_with_recovery(c, retries=3)
    monkeypatch.undo()
    assert calls["n"] == before + 1000  # exactly one attempt

    # an explicit retryable tuple keeps legacy isinstance semantics
    d = (bad * 3.0).sum()
    calls["m"] = 0
    real_d = type(d).evaluate

    def generic_fault(self):
        calls["m"] += 1
        if calls["m"] == 1:
            raise RuntimeError("some generic failure")
        return real_d(self)

    monkeypatch.setattr(type(d), "evaluate", generic_fault)
    with pytest.warns(DeprecationWarning):
        out = evaluate_with_recovery(d, retries=2,
                                     retryable=(RuntimeError,))
    monkeypatch.undo()
    assert calls["m"] == 2
    np.testing.assert_allclose(np.asarray(out.glom()), 48.0)


def test_persistent_compilation_cache_flag(tmp_path):
    """--compilation_cache_dir wires JAX's persistent cache: after an
    initialize() + compile, the cache directory holds entries."""
    import jax

    import spartan_tpu as st
    from spartan_tpu.utils.config import FLAGS

    cache = str(tmp_path / "xla_cache")
    try:
        st.initialize(["--compilation_cache_dir", cache])
        import numpy as np

        x = st.from_numpy(np.arange(4096, dtype=np.float32))
        # a compile long enough to clear the 1s persistence floor is
        # not guaranteed on CPU; assert the config took instead
        assert jax.config.jax_compilation_cache_dir == cache
        float((x * 2.0).sum().glom())
    finally:
        FLAGS.reset_all()
        jax.config.update("jax_compilation_cache_dir", None)
