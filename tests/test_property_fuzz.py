"""Randomized property tests over the geometry + redistribution layer
(SURVEY.md §2.2 extent algebra, §2.3 shuffle): hundreds of random
cases per property, seeded for reproducibility. These are the
invariants every higher layer leans on — region math must be exact and
a scatter-everything shuffle must reconstruct its input bit-for-bit
under any tiling."""

import numpy as np

import spartan_tpu as st
from spartan_tpu.array import tiling as tiling_mod
from spartan_tpu.array.extent import TileExtent


def _rand_extent(rng, shape):
    ul = [rng.randint(0, max(d, 1)) for d in shape]
    lr = [min(d, u + 1 + rng.randint(0, max(d - u, 1)))
          for u, d in zip(ul, shape)]
    return TileExtent(ul, lr, shape)


def test_extent_intersection_matches_set_semantics():
    """intersection == the numpy mask intersection, for 300 random
    pairs across 1-D/2-D/3-D shapes."""
    rng = np.random.RandomState(0)
    for _ in range(300):
        nd = rng.randint(1, 4)
        shape = tuple(rng.randint(1, 9) for _ in range(nd))
        a, b = _rand_extent(rng, shape), _rand_extent(rng, shape)
        mask_a = np.zeros(shape, bool)
        mask_a[a.to_slice()] = True
        mask_b = np.zeros(shape, bool)
        mask_b[b.to_slice()] = True
        both = mask_a & mask_b
        isect = a.intersection(b)
        if isect is None:
            assert not both.any()
        else:
            mask_i = np.zeros(shape, bool)
            mask_i[isect.to_slice()] = True
            assert (mask_i == both).all()
            # symmetric, contained in both, idempotent
            assert b.intersection(a) == isect
            assert a.contains(isect) and b.contains(isect)
            assert isect.intersection(isect) == isect


def test_extent_offset_roundtrip():
    """offset_from/offset_slice index the enclosing block exactly."""
    rng = np.random.RandomState(1)
    for _ in range(200):
        nd = rng.randint(1, 4)
        shape = tuple(rng.randint(2, 10) for _ in range(nd))
        outer = _rand_extent(rng, shape)
        # inner: random sub-extent of outer
        inner_ul = [rng.randint(u, lr) for u, lr in
                    zip(outer.ul, outer.lr)]
        inner_lr = [rng.randint(iu + 1, lr + 1) for iu, lr in
                    zip(inner_ul, outer.lr)]
        inner = TileExtent(inner_ul, inner_lr, shape)
        arr = np.arange(int(np.prod(shape))).reshape(shape)
        block = arr[outer.to_slice()]
        local = inner.offset_from(outer)
        np.testing.assert_array_equal(block[local.to_slice()],
                                      arr[inner.to_slice()])
        np.testing.assert_array_equal(block[outer.offset_slice(inner)],
                                      arr[inner.to_slice()])


def test_tile_grid_partitions_exactly():
    """Every tiling's extents() tile the array: disjoint, covering."""
    rng = np.random.RandomState(2)
    for tile_fn in (tiling_mod.row, tiling_mod.col, tiling_mod.block,
                    tiling_mod.row_t, tiling_mod.block_t):
        for _ in range(30):
            shape = (int(rng.choice([4, 8, 12, 16])),
                     int(rng.choice([2, 4, 6, 8])))
            t = tiling_mod.sanitize(tile_fn(2), shape)
            cover = np.zeros(shape, np.int32)
            for e in t.extents(shape):
                cover[e.to_slice()] += 1
            # uniform coverage (replicated axes repeat regions evenly)
            assert (cover == cover.flat[0]).all() and cover.flat[0] >= 1


def test_sanitize_always_divisible():
    rng = np.random.RandomState(3)
    for _ in range(200):
        nd = rng.randint(1, 4)
        shape = tuple(rng.randint(1, 20) for _ in range(nd))
        axes = [None] * nd
        for i in range(nd):
            if rng.rand() < 0.5:
                axes[i] = tiling_mod.AXIS_ROW if i % 2 == 0 \
                    else tiling_mod.AXIS_COL
        t = tiling_mod.sanitize(tiling_mod.Tiling(axes), shape)
        assert t.divisible(shape)


def test_shuffle_identity_roundtrip_fuzz(mesh2d):
    """Scatter every source tile to its own extent with random tilings
    on both sides: the shuffle must reconstruct the array exactly."""
    rng = np.random.RandomState(4)
    tilings = [tiling_mod.row(2), tiling_mod.col(2), tiling_mod.block(2),
               tiling_mod.row_t(2), tiling_mod.replicated(2)]
    for trial in range(6):
        shape = (int(rng.choice([8, 16, 24])), int(rng.choice([4, 8, 12])))
        a = rng.rand(*shape).astype(np.float32)
        t_in = tilings[trial % len(tilings)]
        t_out = tilings[(trial + 2) % len(tilings)]

        def ident_kernel(ext, block):
            yield ext, block

        out = st.shuffle(st.from_numpy(a, tiling=tiling_mod.sanitize(
            t_in, shape)), ident_kernel, target_shape=shape,
            tiling=tiling_mod.sanitize(t_out, shape), combiner="set")
        np.testing.assert_array_equal(np.asarray(out.glom()), a)


def test_contract_fuzz_vs_einsum_oracle(mesh2d):
    """Random 2-operand contraction specs (batch/free/contraction/
    summed label mixes, random dims) through the PLANNED ContractExpr
    path match np.einsum exactly — the round-5 planner surface under
    random geometry."""
    import string

    rng = np.random.RandomState(6)
    for trial in range(25):
        n_lab = rng.randint(2, 6)
        labs = list(string.ascii_lowercase[:n_lab])
        dims = {c: int(rng.randint(1, 6)) for c in labs}
        nda = rng.randint(1, min(4, n_lab) + 1)
        ndb = rng.randint(1, min(4, n_lab) + 1)
        la = list(rng.choice(labs, nda, replace=False))
        lb = list(rng.choice(labs, ndb, replace=False))
        # output: random subset of the operand labels, no repeats
        pool = sorted(set(la) | set(lb))
        n_out = rng.randint(0, len(pool) + 1)
        lo = list(rng.choice(pool, n_out, replace=False))
        spec = "".join(la) + "," + "".join(lb) + "->" + "".join(lo)
        a = rng.rand(*(dims[c] for c in la)).astype(np.float32)
        b = rng.rand(*(dims[c] for c in lb)).astype(np.float32)
        got = st.einsum(spec, st.from_numpy(a),
                        st.from_numpy(b)).optimized()
        np.testing.assert_allclose(np.asarray(got.glom()),
                                   np.einsum(spec, a, b),
                                   rtol=2e-4, atol=1e-5,
                                   err_msg=spec)


def test_ragged_sort_fuzz(mesh1d):
    """Random lengths (prime, tiny, around multiples of p) and dtypes
    through the distributed sort: oracle-exact, and argsort always a
    valid permutation."""
    rng = np.random.RandomState(7)
    for trial in range(12):
        n = int(rng.choice([1, 2, 7, 8, 9, 63, 64, 65, 997, 1024,
                            2049, 4093]))
        if rng.rand() < 0.5:
            a = rng.randint(-50, 50, n).astype(np.int32)
        else:
            a = (rng.randn(n) * rng.choice([1e-3, 1.0, 1e6])
                 ).astype(np.float32)
        e = st.sort(st.from_numpy(a))
        np.testing.assert_array_equal(np.asarray(e.glom()), np.sort(a),
                                      err_msg=f"n={n} dtype={a.dtype}")
        perm = np.asarray(st.argsort(st.from_numpy(a)).glom())
        assert np.array_equal(np.sort(perm), np.arange(n)), f"n={n}"
        np.testing.assert_array_equal(a[perm], np.sort(a))


def test_masked_ops_fuzz(mesh2d):
    """Random masks/shapes through the mask-aware ops vs numpy.ma."""
    rng = np.random.RandomState(8)
    from spartan_tpu.array.masked import MaskedDistArray

    for trial in range(8):
        m, k, n = (int(rng.randint(2, 10)) for _ in range(3))
        da = rng.rand(m, k).astype(np.float32)
        db = rng.rand(k, n).astype(np.float32)
        ma = rng.rand(m, k) < rng.choice([0.0, 0.3, 0.8])
        mb = rng.rand(k, n) < rng.choice([0.0, 0.3, 0.8])
        got = st.dot(MaskedDistArray(da, ma),
                     MaskedDistArray(db, mb)).glom()
        ref = np.ma.dot(np.ma.masked_array(da, ma),
                        np.ma.masked_array(db, mb))
        np.testing.assert_array_equal(np.ma.getmaskarray(got),
                                      np.ma.getmaskarray(ref))
        np.testing.assert_allclose(np.ma.filled(got, 0.0),
                                   np.ma.filled(ref, 0.0),
                                   rtol=1e-4, atol=1e-5)
        srt = st.sort(MaskedDistArray(da, ma), axis=1).glom()
        ref_s = np.ma.sort(np.ma.masked_array(da, ma), axis=1)
        np.testing.assert_array_equal(np.ma.getmaskarray(srt),
                                      np.ma.getmaskarray(ref_s))
        np.testing.assert_allclose(np.ma.filled(srt, -1.0),
                                   np.ma.filled(ref_s, -1.0), rtol=1e-6)


def test_shuffle_random_emissions_vs_numpy_add(mesh1d):
    """Kernels emitting RANDOM (possibly overlapping) extents with the
    add combiner match a numpy scatter-add oracle."""
    rng = np.random.RandomState(5)
    for trial in range(4):
        src_shape = (16, 6)
        tgt_shape = (int(rng.choice([8, 12])), int(rng.choice([4, 6])))
        a = rng.rand(*src_shape).astype(np.float32)
        # one fixed random plan per source row-block, precomputed so
        # kernel invocations are deterministic
        plans = {}
        for i, e in enumerate(
                tiling_mod.row(2).extents(src_shape)):
            r2 = np.random.RandomState(100 + trial * 50 + i)
            emits = []
            for _ in range(r2.randint(1, 4)):
                te = _rand_extent(r2, tgt_shape)
                emits.append((te, r2.rand(*te.shape).astype(np.float32)))
            plans[e.ul] = emits

        def kern(ext, block):
            for te, data in plans[ext.ul]:
                yield te, data

        oracle = np.zeros(tgt_shape, np.float32)
        for e in tiling_mod.row(2).extents(src_shape):
            for te, data in plans[e.ul]:
                oracle[te.to_slice()] += data
        out = st.shuffle(st.from_numpy(a, tiling=tiling_mod.row(2)),
                         kern, target_shape=tgt_shape, combiner="add")
        np.testing.assert_allclose(np.asarray(out.glom()), oracle,
                                   rtol=1e-5)
