"""N-dimensional coverage (the reference is an N-d array framework —
SURVEY.md §1): 3-D/4-D arrays through map, reduce, slice, transpose,
reshape, scan, and masked ops on the 8-virtual-device mesh, NumPy as
the oracle."""

import numpy as np

import spartan_tpu as st
from spartan_tpu.array import tiling
from spartan_tpu.expr.builtins import BlockedScanExpr


def test_3d_map_reduce_chain(mesh2d):
    rng = np.random.RandomState(0)
    a = rng.rand(8, 6, 4).astype(np.float32)
    b = rng.rand(8, 6, 4).astype(np.float32)
    ea, eb = st.from_numpy(a), st.from_numpy(b)
    np.testing.assert_allclose(
        np.asarray((ea * eb + 1.0).sum(axis=1).glom()),
        (a * b + 1.0).sum(axis=1), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray((ea - eb).max(axis=(0, 2)).glom()),
        (a - b).max(axis=(0, 2)), rtol=1e-5)
    np.testing.assert_allclose(float((ea / (eb + 1.0)).mean().glom()),
                               (a / (b + 1.0)).mean(), rtol=1e-5)


def test_3d_slice_transpose_reshape(mesh2d):
    rng = np.random.RandomState(1)
    a = rng.rand(8, 6, 4).astype(np.float32)
    ea = st.from_numpy(a)
    np.testing.assert_array_equal(np.asarray(ea[2:5, :, 1:3].glom()),
                                  a[2:5, :, 1:3])
    np.testing.assert_array_equal(
        np.asarray(ea.transpose((2, 0, 1)).glom()),
        a.transpose((2, 0, 1)))
    np.testing.assert_array_equal(np.asarray(ea.reshape((48, 4)).glom()),
                                  a.reshape(48, 4))
    np.testing.assert_array_equal(np.asarray(st.ravel(ea).glom()),
                                  a.ravel())


def test_3d_blocked_scan(mesh1d):
    """3-D leading-axis scan takes the blocked distributed path and
    keeps trailing shape."""
    rng = np.random.RandomState(2)
    a = rng.rand(64, 6, 4).astype(np.float32)
    e = st.scan(st.from_numpy(a, tiling=tiling.Tiling(("x", None, None))),
                axis=0)
    assert isinstance(e, BlockedScanExpr)
    np.testing.assert_allclose(np.asarray(e.glom()),
                               np.cumsum(a, axis=0), rtol=1e-4)


def test_4d_elementwise_and_full_reduce(mesh2d):
    rng = np.random.RandomState(3)
    a = rng.rand(8, 4, 2, 6).astype(np.float32)
    ea = st.from_numpy(a)
    np.testing.assert_allclose(float(st.sqrt(ea * ea).sum().glom()),
                               a.sum(), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(ea.sum(axis=(1, 3)).glom()), a.sum(axis=(1, 3)),
        rtol=1e-4)


def test_3d_einsum_batched(mesh2d):
    rng = np.random.RandomState(4)
    a = rng.rand(8, 6, 4).astype(np.float32)
    b = rng.rand(8, 4, 5).astype(np.float32)
    got = st.einsum("bij,bjk->bik", st.from_numpy(a), st.from_numpy(b))
    np.testing.assert_allclose(np.asarray(got.glom()),
                               np.einsum("bij,bjk->bik", a, b),
                               rtol=1e-4)


def test_3d_blocked_scan_trailing_sharded(mesh2d):
    """3-D scan with a SHARDED trailing axis: the blocked path keeps
    the trailing shards (no all-gather of axis 1)."""
    rng = np.random.RandomState(5)
    a = rng.rand(32, 8, 4).astype(np.float32)
    e = st.scan(st.from_numpy(a, tiling=tiling.Tiling(("x", "y", None))),
                axis=0)
    assert isinstance(e, BlockedScanExpr)
    assert e.out_tiling().axes == ("x", "y", None)
    np.testing.assert_allclose(np.asarray(e.glom()),
                               np.cumsum(a, axis=0), rtol=1e-4)
