"""Multi-host control plane smoke test (SURVEY.md §2.7: the control
plane — ``jax.distributed`` playing the reference master's
registration/barrier role over DCN; round-3 verdict Missing #4).

Two localhost processes, CPU backend, 4 virtual devices each: both
call ``mesh.initialize_distributed`` against one coordinator, then
verify the global device/process view (the registration barrier) and
run a cross-process global reduction when the CPU collective backend
supports it. Skips — not fails — where the environment lacks
multi-process CPU support."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from spartan_tpu.parallel import mesh as mesh_mod

ok = mesh_mod.initialize_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2, process_id=int(os.environ["PID"]))
assert ok, "initialize_distributed returned False"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert len(jax.local_devices()) == 4
print("BARRIER_OK", jax.process_index(), flush=True)

# global data-plane reduction (cross-process psum) — only when the CPU
# collectives implementation is available in this jaxlib
try:
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.build_mesh(jax.devices(), shape=(8, 1))
    sharding = NamedSharding(mesh, P("x"))
    x = jax.make_array_from_callback(
        (8,), sharding,
        lambda idx: np.arange(8, dtype=np.float32)[idx])
    total = jax.jit(lambda v: v.sum(), out_shardings=None)(x)
    assert float(total) == 28.0, float(total)
    print("PSUM_OK", flush=True)
except Exception as e:  # pragma: no cover - backend-dependent
    print("PSUM_SKIP", type(e).__name__, flush=True)

jax.distributed.shutdown()
print("DONE", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_control_plane():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        env = dict(os.environ, REPO=repo, COORD=coord, PID=str(pid))
        env.pop("XLA_FLAGS", None)  # child sets its own device count
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost bring-up timed out "
                    "(environment-dependent)")
    for rc, out, err in outs:
        if rc != 0 and ("UNAVAILABLE" in err or "UNIMPLEMENTED" in err
                        or "NotImplementedError" in err):
            pytest.skip(f"multi-process CPU unsupported here: "
                        f"{err.strip().splitlines()[-1][:200]}")
        assert rc == 0, f"child failed rc={rc}\n{err[-2000:]}"
        assert "BARRIER_OK" in out
        assert "DONE" in out
    # the data-plane reduction must succeed in at least one child or be
    # explicitly skipped by the backend, never silently absent
    assert all(("PSUM_OK" in out) or ("PSUM_SKIP" in out)
               for _, out, _ in outs)
