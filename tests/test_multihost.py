"""Multi-host control + data plane test (SURVEY.md §2.7: the control
plane — ``jax.distributed`` playing the reference master's
registration/barrier role over DCN; round-3 verdict Missing #4;
round-4 verdict Weak #5: the psum leg must be a hard assertion where
the backend supports it).

Two localhost processes, CPU backend, 4 virtual devices each: both
call ``mesh.initialize_distributed`` against one coordinator, verify
the global device/process view (the registration barrier), run a
cross-process global reduction, and save a cross-process checkpoint.
A third, SINGLE-process run (8 local devices) then loads that
checkpoint — the elastic-restart story across world sizes. The psum
leg may only be skipped on errors that name an unsupported backend
(UNIMPLEMENTED / UNAVAILABLE / NotImplementedError); any other
failure, or one process passing while the other fails, fails the
test."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from spartan_tpu.parallel import mesh as mesh_mod

ok = mesh_mod.initialize_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2, process_id=int(os.environ["PID"]))
assert ok, "initialize_distributed returned False"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert len(jax.local_devices()) == 4
print("BARRIER_OK", jax.process_index(), flush=True)

# global data-plane reduction (cross-process psum)
try:
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.build_mesh(jax.devices(), shape=(8, 1))
    sharding = NamedSharding(mesh, P("x"))
    x = jax.make_array_from_callback(
        (8,), sharding,
        lambda idx: np.arange(8, dtype=np.float32)[idx])
    total = jax.jit(lambda v: v.sum(), out_shardings=None)(x)
    assert float(total) == 28.0, float(total)
    print("PSUM_OK", flush=True)

    # cross-process checkpoint: every process writes its local shards,
    # process 0 the global manifest (elastic restart loads it later)
    from spartan_tpu.array.distarray import DistArray
    from spartan_tpu.array.tiling import Tiling
    from spartan_tpu.utils import checkpoint

    y = jax.make_array_from_callback(
        (8, 4), NamedSharding(mesh, P("x")),
        lambda idx: (np.arange(32, dtype=np.float32)
                     .reshape(8, 4))[idx])
    try:
        with mesh_mod.use_mesh(mesh):
            checkpoint.save(os.environ["CKPT"],
                            DistArray(y, Tiling(("x", None)), mesh))
            # sparse checkpoint through the same cross-process writer
            from spartan_tpu.array.sparse import SparseDistArray

            rng = np.random.RandomState(3)
            r = rng.randint(0, 24, 100)
            c = rng.randint(0, 20, 100)
            v = rng.rand(100).astype(np.float32)
            sp = SparseDistArray.from_coo(r, c, v, (24, 20))
            checkpoint.save_sparse(os.environ["CKPT"] + "_sp", sp)
        print("CKPT_OK", flush=True)
    except Exception as e:  # checkpoint failures are not psum failures
        print("CKPT_FAIL", type(e).__name__, repr(e)[:300], flush=True)
except Exception as e:  # pragma: no cover - backend-dependent
    print("PSUM_FAIL", type(e).__name__, repr(e)[:300], flush=True)

jax.distributed.shutdown()
print("DONE", flush=True)
"""

_LOADER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
from spartan_tpu.parallel import mesh as mesh_mod
from spartan_tpu.utils import checkpoint

mesh = mesh_mod.build_mesh(jax.devices(), shape=(8, 1))
with mesh_mod.use_mesh(mesh):
    arr = checkpoint.load(os.environ["CKPT"])
    got = np.asarray(arr.glom())
    np.testing.assert_array_equal(
        got, np.arange(32, dtype=np.float32).reshape(8, 4))
    # sparse elastic load: device-resident, re-padded for this mesh
    sp = checkpoint.load_sparse(os.environ["CKPT"] + "_sp")
    rng = np.random.RandomState(3)
    r = rng.randint(0, 24, 100)
    c = rng.randint(0, 20, 100)
    v = rng.rand(100).astype(np.float32)
    oracle = np.zeros((24, 20), np.float32)
    np.add.at(oracle, (r, c), v)
    np.testing.assert_allclose(sp.glom(), oracle, rtol=1e-5)
print("ELASTIC_LOAD_OK", flush=True)
"""

_SOFT_ERRS = ("UNIMPLEMENTED", "UNAVAILABLE", "NotImplementedError",
              # older XLA:CPU words its unimplemented-collectives error
              # as INVALID_ARGUMENT with this message instead
              "aren't implemented on the CPU backend")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_control_and_data_plane(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    ckpt = str(tmp_path / "ckpt")
    procs = []
    for pid in range(2):
        env = dict(os.environ, REPO=repo, COORD=coord, PID=str(pid),
                   CKPT=ckpt)
        env.pop("XLA_FLAGS", None)  # child sets its own device count
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost bring-up timed out "
                    "(environment-dependent)")
    for rc, out, err in outs:
        if rc != 0 and any(s in err for s in _SOFT_ERRS):
            pytest.skip(f"multi-process CPU unsupported here: "
                        f"{err.strip().splitlines()[-1][:200]}")
        assert rc == 0, f"child failed rc={rc}\n{err[-2000:]}"
        assert "BARRIER_OK" in out
        assert "DONE" in out
    # psum leg: hard where supported. A PSUM_FAIL may only name an
    # unsupported-backend error; mixed OK/FAIL across processes always
    # fails (the backend clearly supports it).
    ok_count = sum("PSUM_OK" in out for _, out, _ in outs)
    if ok_count != len(outs):
        fails = [out for _, out, _ in outs if "PSUM_FAIL" in out]
        assert ok_count == 0 and len(fails) == len(outs), \
            f"psum passed on {ok_count}/{len(outs)} processes: {outs}"
        if all(any(s in f for s in _SOFT_ERRS) for f in fails):
            pytest.skip("cross-process CPU collectives unsupported: "
                        + fails[0].strip()[:200])
        raise AssertionError(f"psum failed hard: {fails}")
    # elastic restart: a fresh single-process world loads the
    # checkpoint the two-process world wrote
    assert all("CKPT_OK" in out for _, out, _ in outs), \
        "checkpoint save failed in a child: " + "; ".join(
            line for _, out, _ in outs for line in out.splitlines()
            if "CKPT_FAIL" in line)
    env = dict(os.environ, REPO=repo, CKPT=ckpt)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", _LOADER], env=env,
                       capture_output=True, text=True, timeout=150)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ELASTIC_LOAD_OK" in r.stdout


# -- elastic recovery across processes (ISSUE 7) -------------------------
#
# The process-level analogue of a host loss: a worker process running a
# checkpointed loop is SIGKILLed mid-run, and a SURVIVOR process with a
# smaller device world resumes from the committed snapshot and finishes
# — bit-identical to an uninterrupted run on its own (shrunken) mesh
# (the body is elementwise, so per-iteration math is bitwise
# mesh-independent). The victim's dispatches are slowed through the
# chaos seam so the kill reliably lands mid-loop.

_VICTIM = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import spartan_tpu as st

st.chaos("slow:1.0=0.3")  # stall every dispatch: the kill lands mid-loop
a = np.ones((8, 8), np.float32)
x = st.from_numpy(a * 0.5)
res = st.loop(30, lambda c: c * 1.01 + x, st.from_numpy(a.copy()),
              checkpoint_every=5, checkpoint_path=os.environ["CKPT"])
res.glom()
print("VICTIM_FINISHED", flush=True)
"""

_SURVIVOR = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import spartan_tpu as st

a = np.ones((8, 8), np.float32)
x = st.from_numpy(a * 0.5)
res = st.loop(30, lambda c: c * 1.01 + x, st.from_numpy(a.copy()),
              checkpoint_every=5, resume=os.environ["CKPT"])
out = np.asarray(res.glom())
assert res._resilience["resumed_from"] is not None, \
    "survivor did not restore from the victim's snapshot"
print("RESUMED_FROM", res._resilience["resumed_from"], flush=True)
x2 = st.from_numpy(a * 0.5)
ref = np.asarray(st.loop(30, lambda c: c * 1.01 + x2,
                         st.from_numpy(a.copy())).glom())
np.testing.assert_array_equal(out, ref)
print("SURVIVOR_OK", flush=True)
"""


def test_sigkill_midloop_survivor_resumes_on_smaller_world(tmp_path):
    import json
    import signal
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = str(tmp_path / "elastic_ck")
    env = dict(os.environ, REPO=repo, CKPT=ckpt)
    env.pop("XLA_FLAGS", None)
    victim = subprocess.Popen([sys.executable, "-c", _VICTIM], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
    # wait for a committed snapshot at step >= 10, then SIGKILL — the
    # slowed dispatches guarantee the victim is still mid-loop
    marker = os.path.join(ckpt, "LATEST.json")
    deadline = time.monotonic() + 120
    killed = False
    while time.monotonic() < deadline and victim.poll() is None:
        try:
            with open(marker) as f:
                if json.load(f).get("step", 0) >= 10:
                    victim.send_signal(signal.SIGKILL)
                    killed = True
                    break
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    out, err = victim.communicate(timeout=60)
    if not killed and victim.returncode == 0:
        pytest.skip("victim finished before the kill landed "
                    "(overloaded box); resume leg not exercised")
    if not killed:
        pytest.skip(f"victim died on its own (environment): "
                    f"{err.strip()[-200:]}")
    assert "VICTIM_FINISHED" not in out
    # the survivor world: half the devices, fresh process
    r = subprocess.run([sys.executable, "-c", _SURVIVOR], env=env,
                       capture_output=True, text=True, timeout=150)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "RESUMED_FROM" in r.stdout
    assert "SURVIVOR_OK" in r.stdout
