"""Multi-host control + data plane test (SURVEY.md §2.7: the control
plane — ``jax.distributed`` playing the reference master's
registration/barrier role over DCN; round-3 verdict Missing #4;
round-4 verdict Weak #5: the psum leg must be a hard assertion where
the backend supports it).

Two localhost processes, CPU backend, 4 virtual devices each: both
call ``mesh.initialize_distributed`` against one coordinator, verify
the global device/process view (the registration barrier), run a
cross-process global reduction, and save a cross-process checkpoint.
A third, SINGLE-process run (8 local devices) then loads that
checkpoint — the elastic-restart story across world sizes. The psum
leg may only be skipped on errors that name an unsupported backend
(UNIMPLEMENTED / UNAVAILABLE / NotImplementedError); any other
failure, or one process passing while the other fails, fails the
test."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from spartan_tpu.parallel import mesh as mesh_mod

ok = mesh_mod.initialize_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2, process_id=int(os.environ["PID"]))
assert ok, "initialize_distributed returned False"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert len(jax.local_devices()) == 4
print("BARRIER_OK", jax.process_index(), flush=True)

# global data-plane reduction (cross-process psum)
try:
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.build_mesh(jax.devices(), shape=(8, 1))
    sharding = NamedSharding(mesh, P("x"))
    x = jax.make_array_from_callback(
        (8,), sharding,
        lambda idx: np.arange(8, dtype=np.float32)[idx])
    total = jax.jit(lambda v: v.sum(), out_shardings=None)(x)
    assert float(total) == 28.0, float(total)
    print("PSUM_OK", flush=True)

    # cross-process checkpoint: every process writes its local shards,
    # process 0 the global manifest (elastic restart loads it later)
    from spartan_tpu.array.distarray import DistArray
    from spartan_tpu.array.tiling import Tiling
    from spartan_tpu.utils import checkpoint

    y = jax.make_array_from_callback(
        (8, 4), NamedSharding(mesh, P("x")),
        lambda idx: (np.arange(32, dtype=np.float32)
                     .reshape(8, 4))[idx])
    try:
        with mesh_mod.use_mesh(mesh):
            checkpoint.save(os.environ["CKPT"],
                            DistArray(y, Tiling(("x", None)), mesh))
            # sparse checkpoint through the same cross-process writer
            from spartan_tpu.array.sparse import SparseDistArray

            rng = np.random.RandomState(3)
            r = rng.randint(0, 24, 100)
            c = rng.randint(0, 20, 100)
            v = rng.rand(100).astype(np.float32)
            sp = SparseDistArray.from_coo(r, c, v, (24, 20))
            checkpoint.save_sparse(os.environ["CKPT"] + "_sp", sp)
        print("CKPT_OK", flush=True)
    except Exception as e:  # checkpoint failures are not psum failures
        print("CKPT_FAIL", type(e).__name__, repr(e)[:300], flush=True)
except Exception as e:  # pragma: no cover - backend-dependent
    print("PSUM_FAIL", type(e).__name__, repr(e)[:300], flush=True)

jax.distributed.shutdown()
print("DONE", flush=True)
"""

_LOADER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
from spartan_tpu.parallel import mesh as mesh_mod
from spartan_tpu.utils import checkpoint

mesh = mesh_mod.build_mesh(jax.devices(), shape=(8, 1))
with mesh_mod.use_mesh(mesh):
    arr = checkpoint.load(os.environ["CKPT"])
    got = np.asarray(arr.glom())
    np.testing.assert_array_equal(
        got, np.arange(32, dtype=np.float32).reshape(8, 4))
    # sparse elastic load: device-resident, re-padded for this mesh
    sp = checkpoint.load_sparse(os.environ["CKPT"] + "_sp")
    rng = np.random.RandomState(3)
    r = rng.randint(0, 24, 100)
    c = rng.randint(0, 20, 100)
    v = rng.rand(100).astype(np.float32)
    oracle = np.zeros((24, 20), np.float32)
    np.add.at(oracle, (r, c), v)
    np.testing.assert_allclose(sp.glom(), oracle, rtol=1e-5)
print("ELASTIC_LOAD_OK", flush=True)
"""

_SOFT_ERRS = ("UNIMPLEMENTED", "UNAVAILABLE", "NotImplementedError",
              # older XLA:CPU words its unimplemented-collectives error
              # as INVALID_ARGUMENT with this message instead
              "aren't implemented on the CPU backend")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_control_and_data_plane(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    ckpt = str(tmp_path / "ckpt")
    procs = []
    for pid in range(2):
        env = dict(os.environ, REPO=repo, COORD=coord, PID=str(pid),
                   CKPT=ckpt)
        env.pop("XLA_FLAGS", None)  # child sets its own device count
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost bring-up timed out "
                    "(environment-dependent)")
    for rc, out, err in outs:
        if rc != 0 and any(s in err for s in _SOFT_ERRS):
            pytest.skip(f"multi-process CPU unsupported here: "
                        f"{err.strip().splitlines()[-1][:200]}")
        assert rc == 0, f"child failed rc={rc}\n{err[-2000:]}"
        assert "BARRIER_OK" in out
        assert "DONE" in out
    # psum leg: hard where supported. A PSUM_FAIL may only name an
    # unsupported-backend error; mixed OK/FAIL across processes always
    # fails (the backend clearly supports it).
    ok_count = sum("PSUM_OK" in out for _, out, _ in outs)
    if ok_count != len(outs):
        fails = [out for _, out, _ in outs if "PSUM_FAIL" in out]
        assert ok_count == 0 and len(fails) == len(outs), \
            f"psum passed on {ok_count}/{len(outs)} processes: {outs}"
        if all(any(s in f for s in _SOFT_ERRS) for f in fails):
            pytest.skip("cross-process CPU collectives unsupported: "
                        + fails[0].strip()[:200])
        raise AssertionError(f"psum failed hard: {fails}")
    # elastic restart: a fresh single-process world loads the
    # checkpoint the two-process world wrote
    assert all("CKPT_OK" in out for _, out, _ in outs), \
        "checkpoint save failed in a child: " + "; ".join(
            line for _, out, _ in outs for line in out.splitlines()
            if "CKPT_FAIL" in line)
    env = dict(os.environ, REPO=repo, CKPT=ckpt)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", _LOADER], env=env,
                       capture_output=True, text=True, timeout=150)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ELASTIC_LOAD_OK" in r.stdout


# -- elastic recovery across processes (ISSUE 7) -------------------------
#
# The process-level analogue of a host loss: a worker process running a
# checkpointed loop is SIGKILLed mid-run, and a SURVIVOR process with a
# smaller device world resumes from the committed snapshot and finishes
# — bit-identical to an uninterrupted run on its own (shrunken) mesh
# (the body is elementwise, so per-iteration math is bitwise
# mesh-independent). The victim's dispatches are slowed through the
# chaos seam so the kill reliably lands mid-loop.

_VICTIM = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import spartan_tpu as st

st.chaos("slow:1.0=0.3")  # stall every dispatch: the kill lands mid-loop
a = np.ones((8, 8), np.float32)
x = st.from_numpy(a * 0.5)
res = st.loop(30, lambda c: c * 1.01 + x, st.from_numpy(a.copy()),
              checkpoint_every=5, checkpoint_path=os.environ["CKPT"])
res.glom()
print("VICTIM_FINISHED", flush=True)
"""

_SURVIVOR = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import spartan_tpu as st

a = np.ones((8, 8), np.float32)
x = st.from_numpy(a * 0.5)
res = st.loop(30, lambda c: c * 1.01 + x, st.from_numpy(a.copy()),
              checkpoint_every=5, resume=os.environ["CKPT"])
out = np.asarray(res.glom())
assert res._resilience["resumed_from"] is not None, \
    "survivor did not restore from the victim's snapshot"
print("RESUMED_FROM", res._resilience["resumed_from"], flush=True)
x2 = st.from_numpy(a * 0.5)
ref = np.asarray(st.loop(30, lambda c: c * 1.01 + x2,
                         st.from_numpy(a.copy())).glom())
np.testing.assert_array_equal(out, ref)
print("SURVIVOR_OK", flush=True)
"""


def test_sigkill_midloop_survivor_resumes_on_smaller_world(tmp_path):
    import json
    import signal
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = str(tmp_path / "elastic_ck")
    env = dict(os.environ, REPO=repo, CKPT=ckpt)
    env.pop("XLA_FLAGS", None)
    victim = subprocess.Popen([sys.executable, "-c", _VICTIM], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
    # wait for a committed snapshot at step >= 10, then SIGKILL — the
    # slowed dispatches guarantee the victim is still mid-loop
    marker = os.path.join(ckpt, "LATEST.json")
    deadline = time.monotonic() + 120
    killed = False
    while time.monotonic() < deadline and victim.poll() is None:
        try:
            with open(marker) as f:
                if json.load(f).get("step", 0) >= 10:
                    victim.send_signal(signal.SIGKILL)
                    killed = True
                    break
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    out, err = victim.communicate(timeout=60)
    if not killed and victim.returncode == 0:
        pytest.skip("victim finished before the kill landed "
                    "(overloaded box); resume leg not exercised")
    if not killed:
        pytest.skip(f"victim died on its own (environment): "
                    f"{err.strip()[-200:]}")
    assert "VICTIM_FINISHED" not in out
    # the survivor world: half the devices, fresh process
    r = subprocess.run([sys.executable, "-c", _SURVIVOR], env=env,
                       capture_output=True, text=True, timeout=150)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "RESUMED_FROM" in r.stdout
    assert "SURVIVOR_OK" in r.stdout


# -- N-process elastic re-tiling (ISSUE 14) ------------------------------
#
# The tentpole leg past the single-victim scenario above: an N-process
# (4) ``jax.distributed`` mesh (4 procs x 2 local CPU devices = 8
# global) runs one SPMD checkpointed loop; one process is SIGKILLed at
# a committed snapshot (the host loss — the rest of the world is torn
# down with it, as a scheduler would); a 3-process SURVIVOR world (6
# devices) re-initializes with FLAGS.redistribution_planner on,
# resumes from the snapshot — every carry re-tiled through the
# cross-mesh migration planner (the snapshot's manifest names the
# 8-device grid) — and finishes BIT-STABLE against an uninterrupted
# 3-process run resumed from the same snapshot on the same small mesh.
# Per-rank shard CRCs prove bit-stability without a cross-process
# gather.
#
# Backends whose multi-process computations are unsupported (this
# box's XLA:CPU: "Multiprocess computations aren't implemented") soft-
# skip with the same marker discipline as the psum leg above; the
# tier-1-safe simulated-shrink coverage lives in
# tests/test_elastic_retile.py.

_NPROC_WORLD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import spartan_tpu as st
from spartan_tpu.parallel import mesh as mesh_mod

ok = mesh_mod.initialize_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=int(os.environ["NPROC"]),
    process_id=int(os.environ["PID"]))
assert ok, "initialize_distributed returned False"
print("WORLD_UP", jax.process_index(), jax.device_count(), flush=True)
try:
    mesh = mesh_mod.build_mesh(jax.devices(),
                               shape=(jax.device_count(), 1))
    with mesh_mod.use_mesh(mesh):
        from spartan_tpu.array import tiling
        a = np.arange(192, dtype=np.float32).reshape(24, 8) / 97.0
        x = st.from_numpy(a * 0.5, tiling=tiling.row(2))
        if os.environ.get("SLOW"):
            st.chaos("slow:1.0=0.25")  # the kill lands mid-loop
        res = st.loop(30, lambda c: c * 1.01 + x,
                      st.from_numpy(a.copy(), tiling=tiling.row(2)),
                      checkpoint_every=5,
                      checkpoint_path=os.environ["CKPT"])
        res.glom()
    print("WORLD_FINISHED", flush=True)
except Exception as e:
    msg = f"{type(e).__name__}: {e}"
    soft = any(s in msg for s in (
        "Multiprocess computations", "aren't implemented",
        "UNIMPLEMENTED", "not implemented", "addressable"))
    print("WORLD_UNSUPPORTED" if soft else "WORLD_FAIL",
          msg[:300].replace("\n", " "), flush=True)
    sys.exit(0 if soft else 1)
"""

_NPROC_SURVIVOR = r"""
import os, sys, zlib
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import spartan_tpu as st
from spartan_tpu.parallel import mesh as mesh_mod

ok = mesh_mod.initialize_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=int(os.environ["NPROC"]),
    process_id=int(os.environ["PID"]))
assert ok, "initialize_distributed returned False"
st.FLAGS.redistribution_planner = True  # re-tile through the planner
try:
    mesh = mesh_mod.build_mesh(jax.devices(),
                               shape=(jax.device_count(), 1))
    with mesh_mod.use_mesh(mesh):
        from spartan_tpu.array import tiling
        a = np.arange(192, dtype=np.float32).reshape(24, 8) / 97.0
        x = st.from_numpy(a * 0.5, tiling=tiling.row(2))
        res = st.loop(30, lambda c: c * 1.01 + x,
                      st.from_numpy(a.copy(), tiling=tiling.row(2)),
                      checkpoint_every=5, resume=os.environ["CKPT"])
        val = getattr(res, "value", None) or res.evaluate()
        rec = res._resilience
        if os.environ.get("EXPECT_RESUME"):
            assert rec["resumed_from"] is not None, \
                "survivor did not restore from the world's snapshot"
            migs = rec.get("migrations") or []
            print("MIGRATIONS", len(migs),
                  sum(int(m.get("bytes", 0)) for m in migs), flush=True)
        # per-rank bit-stability: CRC of this process's local shards
        # in device order (same rank -> same devices across runs)
        shards = sorted(val.jax_array.addressable_shards,
                        key=lambda s: s.device.id)
        blob = b"".join(np.ascontiguousarray(s.data).tobytes()
                        for s in shards)
        print("SHARDS_CRC", jax.process_index(),
              zlib.crc32(blob), flush=True)
    print("SURVIVOR_DONE", flush=True)
except Exception as e:
    msg = f"{type(e).__name__}: {e}"
    soft = any(s in msg for s in (
        "Multiprocess computations", "aren't implemented",
        "UNIMPLEMENTED", "not implemented", "addressable"))
    print("SURVIVOR_UNSUPPORTED" if soft else "SURVIVOR_FAIL",
          msg[:300].replace("\n", " "), flush=True)
    sys.exit(0 if soft else 1)
"""


def _spawn_world(script, nproc, env_extra):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nproc):
        env = dict(os.environ, REPO=repo, COORD=coord,
                   NPROC=str(nproc), PID=str(pid), **env_extra)
        env.pop("XLA_FLAGS", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    return procs


def _communicate_all(procs, timeout):
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return None
    return outs


def _run_survivor_world(ckpt, nproc, expect_resume):
    procs = _spawn_world(_NPROC_SURVIVOR, nproc,
                         {"CKPT": ckpt,
                          "EXPECT_RESUME": "1" if expect_resume
                          else ""})
    outs = _communicate_all(procs, timeout=180)
    if outs is None:
        pytest.skip("survivor world bring-up timed out "
                    "(environment-dependent)")
    crcs = {}
    for rc, out, err in outs:
        if "UNSUPPORTED" in out:
            pytest.skip("multi-process CPU computations unsupported "
                        "here: " + out.strip().splitlines()[-1][:200])
        assert rc == 0, f"survivor failed rc={rc}\n{err[-2000:]}\n{out}"
        assert "SURVIVOR_DONE" in out
        for line in out.splitlines():
            if line.startswith("SHARDS_CRC"):
                _, rank, crc = line.split()
                crcs[int(rank)] = int(crc)
    return crcs, outs


def test_nprocess_sigkill_retile_bit_stable(tmp_path):
    """4-process world loses a host mid-checkpointed-loop; a 3-process
    survivor world re-tiles through the redistribution planner and
    finishes bit-stable vs an uninterrupted 3-process resume of the
    same snapshot."""
    import json
    import shutil
    import signal
    import time

    ckpt = str(tmp_path / "world_ck")
    procs = _spawn_world(_NPROC_WORLD, 4, {"CKPT": ckpt, "SLOW": "1"})
    # wait for a committed snapshot, then SIGKILL process 3 (the host
    # loss); the rest of the world is torn down with it
    marker = os.path.join(ckpt, "LATEST.json")
    deadline = time.monotonic() + 150
    killed = False
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            break  # the whole world exited (finished or unsupported)
        try:
            with open(marker) as f:
                if json.load(f).get("step", 0) >= 10:
                    procs[3].send_signal(signal.SIGKILL)
                    killed = True
                    break
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    if killed:
        time.sleep(0.5)  # let survivors hit the dead peer
        for p in procs[:3]:
            if p.poll() is None:
                p.terminate()
    outs = _communicate_all(procs, timeout=60)
    if outs is None:
        pytest.skip("N-process world teardown timed out")
    joined = "\n".join(o for _, o, _ in outs)
    if "WORLD_UNSUPPORTED" in joined:
        pytest.skip("multi-process CPU computations unsupported here: "
                    + next(l for l in joined.splitlines()
                           if "WORLD_UNSUPPORTED" in l)[:200])
    if not killed:
        if "WORLD_FAIL" in joined:
            pytest.fail(f"world failed before the kill: {joined[-2000:]}")
        pytest.skip("world finished before the kill landed "
                    "(overloaded box); N-process leg not exercised")
    assert "WORLD_FINISHED" not in (outs[3][1] or "")
    # two pristine copies of the snapshot: the survivor run and the
    # reference run must resume from the SAME state
    ck_b = str(tmp_path / "ck_survivor")
    ck_c = str(tmp_path / "ck_reference")
    shutil.copytree(ckpt, ck_b)
    shutil.copytree(ckpt, ck_c)
    crc_survivor, s_outs = _run_survivor_world(
        ck_b, 3, expect_resume=True)
    # the survivors re-tiled the 8-device snapshot onto 6 devices
    # through the migration planner
    assert any("MIGRATIONS" in out for _, out, _ in s_outs)
    crc_reference, _ = _run_survivor_world(ck_c, 3, expect_resume=True)
    assert crc_survivor and crc_survivor == crc_reference, (
        crc_survivor, crc_reference)
