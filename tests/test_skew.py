"""Shard-level skew observatory (ISSUE 19): per-shard data-load stats
(the hoisted numerics tile walk), st.skew's straggler attribution on a
deliberately skewed workload, the monitor's sustained-imbalance
anomaly, status/fleet one-liners, sampled bit-equality, and tear-free
skew_* labeled gauges under concurrent writers."""

import threading

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.array import tiling as tiling_mod
from spartan_tpu.expr import base
from spartan_tpu.obs import ledger
from spartan_tpu.obs import monitor
from spartan_tpu.obs import numerics
from spartan_tpu.obs import skew as skew_mod
from spartan_tpu.obs.metrics import REGISTRY, labeled
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _setup(mesh1d):
    saved = {n: getattr(FLAGS, n) for n in (
        "profile_sample_every", "profile_tier", "skew_warn_ratio",
        "cost_ledger", "monitor_drift_patience", "monitor_fleet_dir")}
    FLAGS.cost_ledger = True
    FLAGS.profile_sample_every = 0
    skew_mod.reset()
    monitor.MONITOR.stop()
    monitor.MONITOR.reset()
    ledger.set_profile(None)
    ledger.reset()
    st.serve.shutdown_default()
    yield
    st.serve.shutdown_default()
    monitor.MONITOR.stop()
    monitor.MONITOR.reset()
    skew_mod.reset()
    ledger.set_profile(None)
    ledger.reset()
    for n, v in saved.items():
        setattr(FLAGS, n, v)


def _skewed_array(n=64, d=32):
    """flat_row-tiled array whose FIRST shard is dense and the rest
    all-zero: every per-shard nnz walk must name device 0's shard as
    the hottest (nnz ratio == num_devices)."""
    x = np.zeros((n, d), np.float32)
    x[: n // 8] = 1.5  # exactly the rows of shard 0 on the 8-dev mesh
    return st.from_numpy(x, tiling=tiling_mod.flat_row(2))


# -- per-shard stats: the hoisted numerics walk ---------------------------


def test_per_shard_stats_superset_of_tile_stats():
    """obs/numerics.tile_stats now delegates here (lint rule 17): same
    records, plus the data-skew columns (nbytes / nnz)."""
    arr = _skewed_array().force()
    via_skew = skew_mod.per_shard_stats(arr)
    via_numerics = numerics.tile_stats(arr)
    assert via_skew == via_numerics
    assert len(via_skew) == 8  # one record per device shard
    for rec in via_skew:
        for k in ("device", "index", "nan_count", "inf_count",
                  "absmax", "zero_frac", "size", "nbytes", "nnz"):
            assert k in rec
    # the dense shard carries all the nnz, the rest none
    nnzs = sorted(r["nnz"] for r in via_skew)
    assert nnzs[-1] == 64 * 32 // 8 and sum(nnzs[:-1]) == 0


def test_data_skew_names_dense_shard():
    arr = _skewed_array().force()
    rec = skew_mod.data_skew(arr, label="x")
    assert rec["shards"] == 8
    assert rec["nnz_ratio"] == pytest.approx(8.0)
    assert rec["size_ratio"] == pytest.approx(1.0)  # even split
    dense_dev = max(skew_mod.per_shard_stats(arr),
                    key=lambda r: r["nnz"])["device"]
    assert rec["hottest"] == dense_dev
    assert rec["tiling"] == str(arr.tiling)


# -- the acceptance criterion: attribution on a skewed workload -----------


def test_skew_report_names_hottest_shard_and_straggler():
    """st.skew on the deliberately skewed workload: per-device totals,
    a named hottest shard, per-node ratios with a named straggler
    device, and the data walk calling out the dense tile."""
    x = _skewed_array()
    rep = st.skew(st.dot(x.T, x).sum() + x.sum())
    d = rep.to_dict()
    assert isinstance(rep, st.SkewReport)
    assert len(d["device_totals"]) == 8
    assert d["hottest_shard"] is not None
    assert d["hottest_shard"]["device"] in d["device_totals"]
    assert d["imbalance_ratio"] is not None and d["imbalance_ratio"] >= 1
    assert d["nodes"], "per-node skew rows must exist on the 8-dev mesh"
    for row in d["nodes"]:
        assert row["straggler"] in d["device_totals"]
        assert row["devices"] >= 2 and row["wait_s"] >= 0
    # the data walk names the dense shard's device
    data_rows = [r for r in d["data"] if r.get("nnz_ratio")]
    assert any(r["nnz_ratio"] == pytest.approx(8.0) for r in data_rows)
    text = str(rep)
    assert "shard skew" in text and "straggler" in text
    # recorded for the monitor/status surfaces under the plan digest
    worst = skew_mod.worst_current()
    assert worst is not None and worst["plan"] == d["plan"]
    assert worst["ratio"] == d["imbalance_ratio"]


def test_skew_report_lands_in_explain():
    x = _skewed_array()
    expr = (st.as_expr(x) * 2.0).sum()
    st.skew(expr)
    text = str(st.explain(expr))
    assert "shard skew" in text
    assert "imbalance" in text


def test_skew_advisory_prices_retile_when_past_warn():
    """Past FLAGS.skew_warn_ratio the report carries the priced
    re-tiling suggestion (report-only; plan untouched)."""
    FLAGS.skew_warn_ratio = 1e-9  # any measured ratio trips it
    x = _skewed_array()
    # fresh identical roots before/after: a real re-tile would change
    # x's layout and with it every future plan signature
    key_before, _ = base.plan_signature(st.dot(x.T, x).sum())
    rep = st.skew(st.dot(x.T, x).sum())
    adv = rep.to_dict().get("advisory")
    if adv is not None:  # pricing is best-effort advisory
        assert adv["src"] != adv["dst"]
        assert adv["modeled_cost"] is not None
        assert "ADVISORY" in str(rep)
    key_after, _ = base.plan_signature(st.dot(x.T, x).sum())
    assert key_before == key_after  # report-only: no plan mutation


def test_ledger_grows_skew_columns():
    x = _skewed_array()
    rep = st.skew((st.as_expr(x) + 1.0).sum())
    snap = ledger.snapshot()
    ent = snap["plans"].get(rep.plan)
    assert ent is not None and ent["measured"]["skew"] is not None
    sk = ent["measured"]["skew"]
    assert sk["samples"] >= 1
    assert sk["imbalance_ratio_last"] == rep.imbalance_ratio
    assert sk["imbalance_ratio_max"] >= sk["imbalance_ratio_last"] or \
        sk["imbalance_ratio_max"] == sk["imbalance_ratio_last"]
    assert sk["straggler_wait_mean_s"] >= 0


# -- the monitor's sustained-imbalance detector ---------------------------


def _seed(digest="testplan00", ratio=3.2):
    skew_mod._record(digest, {
        "t": 0.0, "imbalance_ratio": ratio, "straggler_wait_s": 0.01,
        "node": "dot#5", "hottest_shard": "TFRT_CPU_0",
        "data_worst_ratio": 8.0})


def test_monitor_emits_sustained_imbalance_anomaly():
    FLAGS.skew_warn_ratio = 1.5
    FLAGS.monitor_drift_patience = 3
    _seed(ratio=3.2)
    assert monitor.sample() == []  # streak 1
    assert monitor.sample() == []  # streak 2
    out = monitor.sample()  # streak 3 == patience: emit once
    assert [a.kind for a in out] == ["imbalance"]
    a = out[0]
    assert a.key == "testplan00"
    assert a.value == pytest.approx(3.2)
    assert a.threshold == pytest.approx(1.5)
    assert "dot#5" in a.detail and "TFRT_CPU_0" in a.detail
    assert monitor.sample() == []  # sustained breach: no re-emit
    # the ratio series landed in the monitor's store
    series = monitor.MONITOR.store.series(
        "skew_imbalance_ratio:testplan00")
    assert series is not None and series.latest() == pytest.approx(3.2)


def test_monitor_imbalance_below_warn_never_emits():
    FLAGS.skew_warn_ratio = 1.5
    FLAGS.monitor_drift_patience = 2
    _seed(ratio=1.2)  # measured but healthy
    for _ in range(5):
        assert monitor.sample() == []


def test_epoch_fence_resets_imbalance_streak():
    from spartan_tpu.parallel import mesh as mesh_mod

    FLAGS.skew_warn_ratio = 1.5
    FLAGS.monitor_drift_patience = 3
    _seed(ratio=3.2)
    monitor.sample()
    monitor.sample()
    assert monitor.MONITOR.imbalance.streak("testplan00") == 2
    monitor.MONITOR._epoch_seen = mesh_mod.mesh_epoch() - 1
    assert monitor.sample() == []  # fenced tick: quiet by design
    assert monitor.MONITOR.imbalance.streak("testplan00") == 0


# -- status / fleet one-liners --------------------------------------------


def test_status_and_fleet_status_carry_skew_line(tmp_path):
    assert st.status()["skew"] is None  # nothing measured yet
    _seed("planA", ratio=2.0)
    _seed("planB", ratio=4.0)
    s = st.status()
    assert s["skew"] == {"plan": "planB", "ratio": 4.0,
                         "wait_s": 0.01, "node": "dot#5"}

    FLAGS.monitor_fleet_dir = str(tmp_path / "fleet")
    fs = st.fleet_status()
    assert fs["skew_worst"]["plan"] == "planB"
    assert fs["skew_worst"]["rank"] == 0

    # a peer rank reports a worse straggler: the fleet view names it
    import json as _json
    peer = {"rank": 1, "wall_t": 0.0,
            "status": {"skew": {"plan": "planX", "ratio": 9.0,
                                "wait_s": 0.5, "node": "sum#2"}}}
    (tmp_path / "fleet" / "rank_1.json").write_text(_json.dumps(peer))
    fs = st.fleet_status()
    assert fs["skew_worst"] == {"plan": "planX", "ratio": 9.0,
                                "wait_s": 0.5, "node": "sum#2",
                                "rank": 1}


# -- sampling: bit-equality + the serve stamp -----------------------------


def test_sampled_skew_bit_equal_and_same_plan_key():
    """The continuous sampler (skew riding profile's gate) is
    dispatch-time only: same plan key, bit-equal results, and the skew
    state filled as a side effect."""
    x = _skewed_array()

    def expr():
        return st.dot(x.T, x).sum()

    key_off, _ = base.plan_signature(expr())
    ref = expr().evaluate().glom()
    assert skew_mod.current() == {}  # sampling off: no skew state

    FLAGS.profile_sample_every = 1
    key_on, _ = base.plan_signature(expr())
    got = expr().evaluate().glom()
    FLAGS.profile_sample_every = 0

    assert key_on == key_off
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    cur = skew_mod.current()
    assert len(cur) == 1  # the sampled dispatch recorded its plan
    rec = next(iter(cur.values()))
    assert rec.get("data_worst_ratio") == pytest.approx(8.0)
    stamp = skew_mod.take_last_sample()
    assert stamp is not None and stamp["plan"] in cur
    assert skew_mod.take_last_sample() is None  # pop-once


# -- concurrency: tear-free skew_* gauges ---------------------------------


def test_skew_gauges_tear_free_under_8_threads():
    """8 writer threads hammering per-plan skew records racing a
    st.metrics(reset=True) reader: every snapshot is coherent (a
    ratio is one of the exactly-written values, never a torn mix),
    and the Prometheus exposition keeps HELP/TYPE pairs."""
    n_threads, reps = 8, 40
    barrier = threading.Barrier(n_threads + 1)
    errors = []

    def writer(k):
        barrier.wait()
        for i in range(reps):
            try:
                skew_mod._record(f"plan{k:02d}", {
                    "t": float(i), "imbalance_ratio": 1.0 + k,
                    "straggler_wait_s": 0.001 * k, "node": f"dot#{k}",
                    "hottest_shard": f"dev{k}",
                    "data_worst_ratio": None})
            except Exception as e:  # noqa: BLE001 - collected
                errors.append(e)

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    barrier.wait()
    seen = set()
    # keep snapshotting until every writer's series surfaced (the
    # registry keeps keys across reset, so this converges even after
    # the writers finish); yield the GIL so the writers actually run
    import time as _time
    for _ in range(2000):
        snap = st.metrics(reset=True)
        for name, g in snap["gauges"].items():
            # only THIS test's writers (earlier tests leave their own
            # skew gauges in the process-level registry)
            if not name.startswith('skew_imbalance_ratio{plan="plan0'):
                continue
            seen.add(name)
            v = g["value"] if isinstance(g, dict) else g
            # coherent value: exactly one of the written ratios (or
            # the post-reset zero), never a torn intermediate
            assert v in {0.0} | {1.0 + k for k in range(n_threads)}
        if len(seen) == n_threads:
            break
        _time.sleep(0.001)
    for t in threads:
        t.join()
    assert errors == []
    # every writer's labeled series surfaced across the snapshots
    assert len(seen) == n_threads

    # final write round so the exposition has live series to render
    for k in range(n_threads):
        skew_mod._record(f"plan{k:02d}", {
            "t": 0.0, "imbalance_ratio": 1.0 + k,
            "straggler_wait_s": 0.25, "node": f"dot#{k}",
            "hottest_shard": f"dev{k}", "data_worst_ratio": None})
    text = st.metrics(fmt="prometheus")
    assert "# HELP spartan_skew_imbalance_ratio " in text
    assert "# TYPE spartan_skew_imbalance_ratio gauge" in text
    assert "# TYPE spartan_skew_straggler_wait_s gauge" in text
    assert 'spartan_skew_imbalance_ratio{plan="plan03"} 4' in text
    # worst_current agrees with the heaviest writer
    assert skew_mod.worst_current()["plan"] == "plan07"
