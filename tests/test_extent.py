"""Pure unit tests for extent algebra (SURVEY.md §4: 'test_extent'-style —
intersection, offset math; NumPy-free geometry)."""

import numpy as np
import pytest

from spartan_tpu.array import extent
from spartan_tpu.array.extent import TileExtent


def test_basic_properties():
    e = TileExtent((2, 3), (5, 7), (10, 10))
    assert e.shape == (3, 4)
    assert e.size == 12
    assert e.ndim == 2
    assert e.to_slice() == (slice(2, 5), slice(3, 7))


def test_validation():
    with pytest.raises(ValueError):
        TileExtent((5,), (2,), (10,))
    with pytest.raises(ValueError):
        TileExtent((0,), (11,), (10,))
    with pytest.raises(ValueError):
        TileExtent((0, 0), (1,), (10, 10))


def test_hash_eq():
    a = TileExtent((0, 0), (2, 2), (4, 4))
    b = TileExtent((0, 0), (2, 2), (4, 4))
    c = TileExtent((0, 0), (2, 2), (8, 8))
    assert a == b and hash(a) == hash(b)
    assert a != c
    assert len({a, b, c}) == 2


def test_intersection():
    a = TileExtent((0, 0), (5, 5), (10, 10))
    b = TileExtent((3, 3), (8, 8), (10, 10))
    i = a.intersection(b)
    assert i == TileExtent((3, 3), (5, 5), (10, 10))
    # symmetric
    assert b.intersection(a).ul == (3, 3)
    # disjoint
    c = TileExtent((5, 5), (10, 10), (10, 10))
    assert a.intersection(c) is None
    # touching edges are disjoint (half-open)
    d = TileExtent((5, 0), (10, 5), (10, 10))
    assert a.intersection(d) is None


def test_offset_math():
    outer = TileExtent((10, 20), (20, 40), (100, 100))
    inner = TileExtent((12, 25), (15, 30), (100, 100))
    local = inner.offset_from(outer)
    assert local.ul == (2, 5) and local.lr == (5, 10)
    assert outer.offset_slice(inner) == (slice(2, 5), slice(5, 10))
    with pytest.raises(ValueError):
        outer.offset_from(inner)
    assert outer.to_global((0, 0)) == (10, 20)
    assert outer.to_local((10, 20)) == (0, 0)


def test_ravelled_pos_and_axes():
    e = TileExtent((2, 3), (4, 5), (10, 10))
    assert e.ravelled_pos() == 23
    d = e.drop_axis(1)
    assert d.ul == (2,) and d.lr == (4,) and d.array_shape == (10,)
    a = d.add_axis(1, 5)
    assert a.ul == (2, 0) and a.lr == (4, 5)


def test_from_slice():
    e = extent.from_slice((slice(1, 3), 4), (10, 10))
    assert e.ul == (1, 4) and e.lr == (3, 5)
    e = extent.from_slice(slice(None), (7, 3))
    assert e.ul == (0, 0) and e.lr == (7, 3)
    e = extent.from_slice((slice(-3, None),), (10,))
    assert e.ul == (7,) and e.lr == (10,)
    e = extent.from_slice(-1, (10,))
    assert e.ul == (9,) and e.lr == (10,)
    with pytest.raises(ValueError):
        extent.from_slice(slice(0, 10, 2), (10,))
    with pytest.raises(IndexError):
        extent.from_slice((0, 0, 0), (10, 10))


def test_compute_splits():
    assert extent.compute_splits(10, 2) == [(0, 5), (5, 10)]
    assert extent.compute_splits(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert extent.compute_splits(2, 5) == [(0, 1), (1, 2)]
    # n splits capped at dim
    assert len(extent.compute_splits(3, 8)) == 3


def test_tile_grid_covers():
    grid = extent.tile_grid((10, 12), (3, 2))
    assert len(grid) == 6
    assert extent.is_complete((10, 12), grid)
    # row-major tile order
    assert grid[0].ul == (0, 0)
    assert grid[1].ul == (0, 6)
    assert grid[2].ul == (4, 0)


def test_tiles_like_hint():
    grid = extent.tiles_like_hint((100, 100), (50, 100))
    assert len(grid) == 2
    assert grid[0].shape == (50, 100)
    assert extent.is_complete((100, 100), grid)


def test_find_overlapping():
    grid = extent.tile_grid((10, 10), (2, 2))
    region = TileExtent((4, 4), (6, 6), (10, 10))
    hits = extent.find_overlapping(grid, region)
    assert len(hits) == 4
    region2 = TileExtent((0, 0), (5, 5), (10, 10))
    assert extent.find_overlapping(grid, region2) == [grid[0]]


def test_fetch_assembly_oracle():
    """Assembling a region from grid tiles reproduces the NumPy slice —
    the DistArray.fetch metadata path (SURVEY.md §3.5)."""
    arr = np.arange(100).reshape(10, 10)
    grid = extent.tile_grid((10, 10), (3, 3))
    region = TileExtent((2, 3), (9, 8), (10, 10))
    out = np.zeros(region.shape, arr.dtype)
    for t in grid:
        ix = t.intersection(region)
        if ix is None:
            continue
        out[region.offset_slice(ix)] = arr[t.to_slice()][t.offset_slice(ix)]
    np.testing.assert_array_equal(out, arr[region.to_slice()])
