"""Warm-start persistence (spartan_tpu/persist, docs/WARMSTART.md).

The contract under test: a populated store lets a fresh process (or a
cache-cleared one) serve its plan set with ZERO XLA recompiles and
bit-equal results — and EVERY hostile-store scenario (truncated /
corrupt entry, version or fingerprint skew, ``io`` chaos on load and
store, a concurrent writer's lease, a missing prewarm entry, a dead
mesh epoch) degrades to a normal recompile with the reason surfaced
in the ``persist_*`` metrics and ``st.explain`` — never a crash,
never a wrong result.
"""

import json
import os
import subprocess
import sys
import zlib

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu import persist
from spartan_tpu.expr import base as expr_base
from spartan_tpu.obs.metrics import REGISTRY, labeled
from spartan_tpu.utils import profiling

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counters():
    return REGISTRY.counter_values()


class _Delta:
    """Counter deltas vs construction time (the registry is global and
    accumulates across tests)."""

    def __init__(self):
        self.base = REGISTRY.counter_values()

    def __call__(self, name, **labels):
        key = labeled(name, **labels) if labels else name
        return (REGISTRY.counter_values().get(key, 0)
                - self.base.get(key, 0))


def _fresh(tmp_path, name="store"):
    """Point the store at a fresh dir (the conftest fixture restores
    the flag and resets the singleton after the test)."""
    d = str(tmp_path / name)
    st.FLAGS.persist_cache_dir = d
    # also drop the in-memory plan/compile caches: identical-structure
    # exprs from OTHER tests would hit the plan cache and the persist
    # path (a miss-path feature) would never run
    _restart()
    return d


def _restart():
    """Simulate a process restart for the evaluation stack: drop the
    in-memory plan/compile caches and the persist singleton's memos
    (the on-disk store survives, like a real restart)."""
    expr_base.clear_compile_cache()
    persist.reset()
    profiling.reset_counters()


def _plan_set(seed=0, n=48):
    rng = np.random.RandomState(seed)
    x = st.from_numpy(rng.rand(n, n).astype(np.float32))
    y = st.from_numpy(rng.rand(n, n).astype(np.float32))
    return [
        ((x + y) * 3.0 - x).sum(),
        st.dot(x, y).sum(axis=0),
    ]


def _entry_dirs(d):
    return sorted(p for p in os.listdir(d) if p.startswith("entry_")
                  and not p.endswith(".lease") and ".tmp-" not in p)


def _manifest_path(d, entry):
    return os.path.join(d, entry, "manifest.json")


def _rewrite_manifest(d, entry, mutate):
    mp = _manifest_path(d, entry)
    with open(mp) as f:
        manifest = json.load(f)
    mutate(manifest)
    with open(mp, "w") as f:
        json.dump(manifest, f)


# -- the happy path ------------------------------------------------------


def test_store_off_by_default(mesh2d):
    assert st.FLAGS.persist_cache_dir == ""
    assert persist.active() is None
    out = _plan_set()[0].evaluate().glom()
    assert np.isfinite(out).all()
    assert persist.stats() == {"enabled": False}


def test_round_trip_zero_recompiles_bit_equal(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    delta = _Delta()
    cold = [e.evaluate().glom() for e in _plan_set()]
    assert len(_entry_dirs(d)) == 2
    assert delta("persist_stores") == 2

    _restart()
    warm = [e.evaluate().glom() for e in _plan_set()]
    assert profiling.counters().get("compiles", 0) == 0, \
        "a populated store must serve the plan set with ZERO recompiles"
    assert delta("persist_hits") == 2
    for c, w in zip(cold, warm):
        np.testing.assert_array_equal(c, w)  # bit-equal, not allclose


def test_explain_names_disk_hit_vs_compile(mesh2d, tmp_path):
    _fresh(tmp_path)
    e = _plan_set()[0]
    e.evaluate()
    rep = st.explain(_plan_set()[0], cost=False).to_dict()
    assert rep["persist"]["source"] == "compile"
    assert rep["persist"]["stored"] is True

    _restart()
    rep = st.explain(_plan_set()[0], cost=False)
    assert rep.to_dict()["persist"]["source"] == "disk"
    assert "persist: disk hit" in str(rep)
    # and the explain pre-plan seeded the cache: evaluating now
    # dispatches the restored executable
    out = _plan_set()[0].evaluate().glom()
    assert np.isfinite(out).all()
    assert profiling.counters().get("compiles", 0) == 0


def test_steady_state_hits_never_touch_the_store(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    e = _plan_set()[0]
    e.evaluate()
    delta = _Delta()
    for _ in range(3):
        out = _plan_set()[0].evaluate().glom()
    assert np.isfinite(out).all()
    assert delta("persist_hits") == 0
    assert delta("persist_misses") == 0
    assert len(_entry_dirs(d)) == 1


def test_donation_variant_composes_with_restored_plan(mesh2d, tmp_path):
    _fresh(tmp_path)
    rng = np.random.RandomState(3)
    a_np = rng.rand(32, 32).astype(np.float32)
    a = st.from_numpy(a_np)
    (st.as_expr(a) * 2.0).evaluate()

    _restart()
    a2 = st.from_numpy(a_np)
    expr = st.as_expr(a2) * 2.0
    out = expr.evaluate(donate=[a2]).glom()  # donation variant compiles
    np.testing.assert_array_equal(out, a_np * 2.0)
    with pytest.raises(Exception):
        a2.glom()  # donated buffer invalidated as usual


# -- hostile stores ------------------------------------------------------


def test_corrupt_exec_rejected_crc_named(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    e = _plan_set()[0]
    expected = e.evaluate().glom()
    entry = _entry_dirs(d)[0]
    blob = os.path.join(d, entry, "exec.bin")
    with open(blob, "r+b") as f:  # flip bytes mid-file: CRC must trip
        f.seek(10)
        f.write(b"\xde\xad\xbe\xef")

    _restart()
    delta = _Delta()
    out = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out, expected)  # recompile fallback
    assert delta("persist_load_errors", reason="crc") == 1
    assert delta("persist_hits") == 0
    rep = st.explain(_plan_set()[0], cost=False).to_dict()
    assert rep["persist"]["source"] == "compile"


def test_truncated_entry_rejected(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    expected = _plan_set()[0].evaluate().glom()
    entry = _entry_dirs(d)[0]
    blob = os.path.join(d, entry, "trees.pkl")
    data = open(blob, "rb").read()
    with open(blob, "wb") as f:
        f.write(data[: max(1, len(data) // 2)])

    _restart()
    delta = _Delta()
    out = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out, expected)
    assert delta("persist_load_errors", reason="crc") == 1


def test_version_skew_rejected(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    expected = _plan_set()[0].evaluate().glom()
    entry = _entry_dirs(d)[0]
    _rewrite_manifest(d, entry, lambda m: m.update(version=999))

    _restart()
    delta = _Delta()
    out = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out, expected)
    assert delta("persist_load_errors", reason="version") == 1


def test_fingerprint_skew_rejected(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    expected = _plan_set()[0].evaluate().glom()
    entry = _entry_dirs(d)[0]
    _rewrite_manifest(
        d, entry,
        lambda m: m["fingerprint"].update(jax="0.0.0-foreign"))

    _restart()
    delta = _Delta()
    out = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out, expected)
    assert delta("persist_load_errors", reason="fingerprint") == 1
    rep = st.explain(_plan_set()[0], cost=False).to_dict()
    assert rep["persist"]["reason"] == "fingerprint"


def test_plan_meta_mismatch_rejected_and_purged(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    expected = _plan_set()[0].evaluate().glom()
    entry = _entry_dirs(d)[0]
    pj = os.path.join(d, entry, "plan.json")
    with open(pj) as f:
        meta = json.load(f)
    meta["arg_order"] = list(reversed(meta["arg_order"] or [0, 1]))
    raw = json.dumps(meta, sort_keys=True).encode()
    with open(pj, "wb") as f:
        f.write(raw)
    # keep the CRC honest so ONLY the belt check can reject it
    _rewrite_manifest(
        d, entry,
        lambda m: m["files"].update(
            {"plan.json": {"crc32": zlib.crc32(raw),
                           "bytes": len(raw)}}))

    _restart()
    delta = _Delta()
    out = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out, expected)
    assert delta("persist_load_errors", reason="meta_mismatch") == 1
    # the hostile entry was purged, then the recompile re-persisted a
    # healthy one (self-healing): the next restart hits cleanly
    assert delta("persist_stores") == 1
    _restart()
    delta = _Delta()
    np.testing.assert_array_equal(_plan_set()[0].evaluate().glom(),
                                  expected)
    assert delta("persist_hits") == 1


def test_io_chaos_on_load_degrades_to_recompile(mesh2d, tmp_path):
    _fresh(tmp_path)
    expected = _plan_set()[0].evaluate().glom()

    _restart()
    delta = _Delta()
    with st.chaos("io@0"):
        out = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out, expected)
    assert delta("persist_load_errors", reason="io") == 1
    assert profiling.counters().get("compiles", 0) == 1


def test_io_chaos_on_store_never_fails_evaluate(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    delta = _Delta()
    with st.chaos("io@0"):
        out = _plan_set()[0].evaluate().glom()
    assert np.isfinite(out).all()
    assert _entry_dirs(d) == []  # nothing persisted...
    assert delta("persist_store_errors", reason="io") == 1
    # ...and a later recompile re-persists once the fault clears
    _restart()
    out2 = _plan_set()[0].evaluate().glom()
    np.testing.assert_array_equal(out2, out)
    assert len(_entry_dirs(d)) == 1


def test_live_lease_blocks_writer_stale_lease_broken(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    store = persist.active()
    # a live lease from "another replica": this process must skip
    digest = "f" * 40
    lease = os.path.join(d, f"entry_{digest}.lease")
    with open(lease, "w") as f:
        f.write("99999")
    assert store.save(digest, {"mesh_epoch": 0}, {"x": 1}, b"bytes",
                      (None, None)) is False
    assert not store.has(digest)
    # a STALE lease (writer died mid-persist) is broken and the write
    # proceeds
    old = 10.0
    os.utime(lease, (old, old))
    assert store.save(digest, {"mesh_epoch": 0}, {"x": 1}, b"bytes",
                      (None, None)) is True
    assert store.has(digest)
    assert not os.path.exists(lease)


def test_unstable_plan_key_skips_persistence(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    rng = np.random.RandomState(5)
    arr = st.from_numpy(rng.rand(16, 16).astype(np.float32))
    marker = object()  # lands in the closure cells via fn_key

    def fn(v):
        return v * (1.0 if marker else 0.0)

    delta = _Delta()
    out = st.map(fn, arr).evaluate().glom()
    np.testing.assert_array_equal(out, np.asarray(arr.glom()))
    assert delta("persist_unstable_keys") >= 1
    assert _entry_dirs(d) == []  # not persistable, not persisted


def test_dead_epoch_entries_purged_by_evict_stale_plans(
        mesh2d, tmp_path):
    d = _fresh(tmp_path)
    _plan_set()[0].evaluate()
    entry = _entry_dirs(d)[0]
    # make the entry claim a long-dead mesh epoch (as a pre-rebuild
    # writer would have): evict_stale_plans must reap it on disk
    _rewrite_manifest(d, entry, lambda m: m.update(mesh_epoch=-1))
    expr_base.evict_stale_plans()
    assert entry not in _entry_dirs(d)
    assert persist.last_evicted() == 1
    # idempotent + still no crash on an empty store
    expr_base.evict_stale_plans()
    assert persist.last_evicted() == 0


# -- prewarm -------------------------------------------------------------


def test_prewarm_restores_plan_set_off_request_path(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    cold = [e.evaluate().glom() for e in _plan_set()]
    digests = persist.active().digests()
    manifest_path = str(tmp_path / "prewarm.json")
    assert persist.write_manifest(manifest_path) == 2

    _restart()
    eng = st.serve.ServeEngine(workers=1)
    try:
        stats = eng.prewarm(manifest_path)
        assert stats["loaded"] == 2 and stats["errors"] == 0
        assert persist.stats()["preloaded"] == 2
        futs = [eng.submit(e) for e in _plan_set()]
        warm = [f.glom() for f in futs]
    finally:
        eng.stop()
    assert profiling.counters().get("compiles", 0) == 0
    for c, w in zip(cold, warm):
        np.testing.assert_array_equal(c, w)
    # the flight recorder names the disk hit for the built requests
    kinds = [ev.kind for ev in st.obs.flight.events()]
    assert "persist" in kinds
    assert sorted(digests) == sorted(persist.active().digests())


def test_prewarm_missing_and_corrupt_entries_isolated(
        mesh2d, tmp_path):
    d = _fresh(tmp_path)
    _plan_set()[0].evaluate()
    good = _entry_dirs(d)[0][len("entry_"):]
    bad_dir = _entry_dirs(d)[0]
    # a second, corrupt entry + a missing digest in the manifest
    corrupt = "a" * 40
    import shutil

    shutil.copytree(os.path.join(d, bad_dir),
                    os.path.join(d, f"entry_{corrupt}"))
    with open(os.path.join(d, f"entry_{corrupt}", "exec.bin"),
              "r+b") as f:
        f.seek(4)
        f.write(b"\x00\x00\x00\x00")
    _restart()
    delta = _Delta()
    stats = persist.prewarm([good, corrupt, "b" * 40])
    assert stats["loaded"] == 1
    assert stats["errors"] == 1  # corrupt: counted, isolated
    assert stats["missing"] == 1  # absent: counted, isolated
    assert delta("persist_prewarm_errors", reason="crc") == 1


def test_prewarm_per_entry_timeout(mesh2d, tmp_path, monkeypatch):
    _fresh(tmp_path)
    _plan_set()[0].evaluate()
    _restart()
    store = persist.active()
    import time as _time

    def slow_preload(digest, fp):
        _time.sleep(0.5)
        return True

    delta = _Delta()
    monkeypatch.setattr(store, "preload", slow_preload)
    stats = persist.prewarm("all", timeout_s=0.05)
    assert stats["errors"] == stats["total"] >= 1
    assert delta("persist_prewarm_errors", reason="timeout") >= 1


def test_prewarm_noop_with_store_off(mesh2d):
    assert persist.active() is None
    eng = st.serve.ServeEngine(workers=1)
    try:
        stats = eng.prewarm("all")
    finally:
        eng.stop()
    assert stats["loaded"] == 0 and stats["errors"] == 0


# -- cross-process (the real restart + the shared cache dir) -------------

_CHILD = r"""
import json, sys
import numpy as np
sys.path.insert(0, "@REPO@")
import spartan_tpu as st
from spartan_tpu.utils import profiling
st.FLAGS.persist_cache_dir = sys.argv[1]
rng = np.random.RandomState(0)
x = st.from_numpy(rng.rand(48, 48).astype(np.float32))
y = st.from_numpy(rng.rand(48, 48).astype(np.float32))
outs = [((x + y) * 3.0 - x).sum().glom(),
        st.dot(x, y).sum(axis=0).glom()]
m = st.metrics()["counters"]
print(json.dumps({
    "compiles": profiling.counters().get("compiles", 0),
    "hits": m.get("persist_hits", 0),
    "stores": m.get("persist_stores", 0),
    "digest": [float(np.asarray(o).sum()) for o in outs],
    "bytes": [np.asarray(o).tobytes().hex()[:64] for o in outs],
}))
"""


def _run_child(cache_dir, timeout=180):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    return subprocess.Popen(
        [sys.executable, "-c", _CHILD.replace("@REPO@", REPO),
         cache_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)


def test_warm_restart_across_processes_acceptance(tmp_path):
    """The acceptance criterion: a FRESH process with a populated
    store serves the plan set with zero recompiles and bit-equal
    results vs the cold run."""
    d = str(tmp_path / "shared")
    p = _run_child(d)
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err
    cold = json.loads(out.strip().splitlines()[-1])
    assert cold["compiles"] == 2 and cold["stores"] == 2, (cold, err)

    p = _run_child(d)
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err
    warm = json.loads(out.strip().splitlines()[-1])
    assert warm["compiles"] == 0, (warm, err)
    assert warm["hits"] == 2
    assert warm["bytes"] == cold["bytes"]  # bit-equal across processes


def test_two_processes_share_one_cache_dir_concurrently(tmp_path):
    """Two replicas racing the same (empty) store: lock-free readers +
    lease writers — no crash, both bit-equal, and the store ends up
    consistent (each entry written exactly once per lease round)."""
    d = str(tmp_path / "shared")
    procs = [_run_child(d), _run_child(d)]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err
        results.append(json.loads(out.strip().splitlines()[-1]))
    assert results[0]["bytes"] == results[1]["bytes"]
    # the store is complete and immediately usable by a third process
    p = _run_child(d)
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err
    warm = json.loads(out.strip().splitlines()[-1])
    assert warm["compiles"] == 0 and warm["hits"] == 2, (warm, err)
    assert warm["bytes"] == results[0]["bytes"]


# -- GC policy: size/TTL bounds (ISSUE 14 satellite) ----------------------


def _age_entry(d, entry, seconds):
    """Backdate an entry's manifest mtime (the GC's LRU clock)."""
    mp = _manifest_path(d, entry)
    old = os.path.getmtime(mp) - seconds
    os.utime(mp, (old, old))


def test_gc_off_by_default(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    assert st.FLAGS.persist_max_bytes == 0
    assert st.FLAGS.persist_ttl_s == 0.0
    for e in _plan_set():
        e.evaluate().glom()
    n = len(_entry_dirs(d))
    assert n >= 2
    assert persist.maybe_gc() == 0  # unbounded: sweep is a no-op
    assert len(_entry_dirs(d)) == n


def test_gc_ttl_evicts_stale_entries(mesh2d, tmp_path):
    d = _fresh(tmp_path)
    delta = _Delta()
    for e in _plan_set():
        e.evaluate().glom()
    entries = _entry_dirs(d)
    assert len(entries) >= 2
    _age_entry(d, entries[0], seconds=3600)
    st.FLAGS.persist_ttl_s = 60.0
    try:
        n = persist.maybe_gc()
    finally:
        st.FLAGS.persist_ttl_s = 0.0
    assert n == 1
    assert entries[0] not in _entry_dirs(d)
    assert delta("persist_evictions") == 1


def test_gc_size_bound_evicts_lru_first(mesh2d, tmp_path):
    """Over the byte budget, the LEAST-recently-used entry (manifest
    mtime) goes first; the freshly-stored entry is protected."""
    d = _fresh(tmp_path)
    for e in _plan_set():
        e.evaluate().glom()
    entries = _entry_dirs(d)
    assert len(entries) >= 2
    store = persist.active()
    total = store.total_bytes()
    # age the FIRST entry far back; bound the store so exactly one
    # must go — LRU says the aged one
    _age_entry(d, entries[0], seconds=1000)
    sizes = {dg: b for _, b, dg in store.entry_stats()}
    victim_digest = entries[0][len("entry_"):]
    st.FLAGS.persist_max_bytes = total - 1
    try:
        n = persist.maybe_gc()
    finally:
        st.FLAGS.persist_max_bytes = 0
    assert n >= 1
    assert entries[0] not in _entry_dirs(d)
    assert store.total_bytes() <= total - sizes[victim_digest]


def test_gc_load_refreshes_recency(mesh2d, tmp_path):
    """A USED entry does not age out: successful loads touch the
    manifest mtime, so TTL eviction tracks use, not creation."""
    d = _fresh(tmp_path)
    exprs = _plan_set()
    for e in exprs:
        e.evaluate().glom()
    entries = _entry_dirs(d)
    for e in entries:
        _age_entry(d, e, seconds=3600)
    # a restart re-loads the first plan from disk -> refreshes it
    _restart()
    _plan_set()[0].evaluate().glom()
    refreshed = [e for e in _entry_dirs(d)
                 if os.path.getmtime(_manifest_path(d, e))
                 > os.path.getmtime(_manifest_path(
                     d, entries[0])) or e == entries[0]]
    st.FLAGS.persist_ttl_s = 60.0
    try:
        persist.maybe_gc()
    finally:
        st.FLAGS.persist_ttl_s = 0.0
    left = _entry_dirs(d)
    assert len(left) == 1  # only the re-used entry survived the TTL


def test_gc_runs_after_store_and_protects_new_entry(mesh2d, tmp_path):
    """End to end: with a tiny byte budget, persisting the second plan
    evicts the first (LRU) but never the entry just written."""
    d = _fresh(tmp_path)
    st.FLAGS.persist_max_bytes = 1  # nothing fits, newest protected
    delta = _Delta()
    try:
        a, b = _plan_set()
        a.evaluate().glom()
        first = _entry_dirs(d)
        assert len(first) == 1  # the just-written entry is protected
        b.evaluate().glom()
        second = _entry_dirs(d)
        # the older entry was evicted; the newest survives its own GC
        assert len(second) == 1 and second != first
    finally:
        st.FLAGS.persist_max_bytes = 0
    assert delta("persist_evictions") >= 1
    # results stay correct throughout (availability over reuse)
    out = np.asarray(_plan_set()[0].evaluate().glom())
    assert np.isfinite(out).all()
