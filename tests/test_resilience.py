"""Resilient execution (ISSUE 5): fault injection at the real seams,
classified retry, OOM degradation ladder, crash-safe checkpoints, and
st.loop checkpoint/resume — the full fault matrix
{transient, deterministic, OOM, checkpoint-IO} x {evaluate, st.loop},
exercised deterministically on CPU via ``st.chaos``."""

import json
import os

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.resilience import classify as cls
from spartan_tpu.resilience import engine, faults
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _setup(mesh2d):
    saved = {n: getattr(FLAGS, n) for n in (
        "retry_backoff_s", "retry_max", "retry_budget", "oom_degrade",
        "crash_dump_path", "dispatch_timeout_s", "resilience",
        "loop_restore_max", "opt_map_fusion", "opt_reduce_fusion")}
    FLAGS.retry_backoff_s = 0.0
    engine.reset()
    st.chaos_clear()
    yield
    st.chaos_clear()
    engine.reset()
    for n, v in saved.items():
        setattr(FLAGS, n, v)


def _counter(name):
    return st.metrics()["counters"].get(name, 0)


def _fresh(shape=(16, 16), seed=0, scale=1.0):
    rng = np.random.RandomState(seed)
    a = (rng.rand(*shape).astype(np.float32) + 1.0) * scale
    return a, st.from_numpy(a)


# -- classifier ----------------------------------------------------------


def test_classifier_table():
    assert cls.classify(RuntimeError(
        "UNAVAILABLE: socket closed")) == cls.TRANSIENT
    assert cls.classify(RuntimeError(
        "DEADLINE_EXCEEDED: operation timed out")) == cls.TRANSIENT
    assert cls.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes")) == cls.OOM
    assert cls.classify(MemoryError()) == cls.OOM
    assert cls.classify(OSError("disk full")) == cls.IO
    assert cls.classify(ValueError("bad axis")) == cls.DETERMINISTIC
    assert cls.classify(RuntimeError(
        "INVALID_ARGUMENT: bad layout")) == cls.DETERMINISTIC
    # XLA INTERNAL errors are deliberately NOT transient
    assert cls.classify(RuntimeError(
        "INTERNAL: compiler bug")) == cls.DETERMINISTIC
    # injected faults classify like their real counterparts
    assert cls.classify(
        faults.InjectedTransientError("x")) == cls.TRANSIENT
    assert cls.classify(faults.InjectedOOMError("x")) == cls.OOM
    assert cls.classify(
        faults.InjectedCompileError("x")) == cls.DETERMINISTIC
    assert cls.classify(
        faults.InjectedCheckpointError("x")) == cls.IO


def test_chaos_spec_parsing():
    plan = faults.ChaosPlan("transient@2,oom@4x3,slow@1=0.25,io@0", 7)
    kinds = [s.kind for s in plan.specs]
    assert kinds == ["transient", "oom", "slow", "io"]
    assert plan.specs[1].at == 4 and plan.specs[1].count == 3
    assert plan.specs[2].dur == 0.25
    with pytest.raises(ValueError, match="bad fault token"):
        faults.ChaosPlan("explode@1", 0)
    with pytest.raises(ValueError, match="needs a deterministic"):
        faults.ChaosPlan("transient", 0)


def test_chaos_probabilistic_is_seed_deterministic():
    a = faults.FaultSpec("transient:0.3")
    hits1 = [a.hits(i, 42) for i in range(64)]
    hits2 = [a.hits(i, 42) for i in range(64)]
    hits3 = [a.hits(i, 43) for i in range(64)]
    assert hits1 == hits2  # same seed -> same fault sequence
    assert hits1 != hits3  # different seed -> different sequence
    assert 2 < sum(hits1) < 40  # roughly p=0.3


# -- fault matrix: {transient, oom, deterministic} x {evaluate, loop} ----


def _run_case(mode, spec):
    """Build a fresh structure, run it fault-free, then run an
    identical structure under ``spec``; return (clean, faulted)."""
    if mode == "evaluate":
        a, x = _fresh(seed=3)
        clean = np.asarray(((x * 2.0 + 1.0).sum(axis=0)).glom())
        with st.chaos(spec):
            a2, x2 = _fresh(seed=3)
            faulted = np.asarray(((x2 * 2.0 + 1.0).sum(axis=0)).glom())
        return clean, faulted
    a, x = _fresh(shape=(8, 8), seed=4)

    def body(c):
        return c * 1.01 + x

    clean = np.asarray(st.loop(5, body, st.from_numpy(a)).glom())
    with st.chaos(spec):
        faulted = np.asarray(st.loop(5, body, st.from_numpy(a)).glom())
    return clean, faulted


@pytest.mark.parametrize("mode", ["evaluate", "loop"])
def test_matrix_transient_recovers(mode):
    before = _counter("resilience_retries")
    clean, faulted = _run_case(mode, "transient@0")
    assert _counter("resilience_retries") - before >= 1
    np.testing.assert_array_equal(clean, faulted)


@pytest.mark.parametrize("mode", ["evaluate", "loop"])
def test_matrix_oom_degrades(mode):
    before = _counter("resilience_degrades")
    clean, faulted = _run_case(mode, "oom@0")
    assert _counter("resilience_degrades") - before >= 1
    np.testing.assert_allclose(clean, faulted, rtol=1e-6)


@pytest.mark.parametrize("mode", ["evaluate", "loop"])
def test_matrix_deterministic_fails_fast(mode):
    # compile-site faults fire only on a FRESH compile, so these
    # structures use shapes no other test compiles (a cache hit would
    # skip the seam — which is itself the right production behavior)
    before = _counter("resilience_retries")
    with st.chaos("compile@0"):
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
            if mode == "evaluate":
                _, x = _fresh(shape=(24, 8), seed=20)
                (x * 2.0 + 1.0).sum(axis=0).glom()
            else:
                _, x = _fresh(shape=(12, 4), seed=21)
                st.loop(5, lambda c: c * 1.5 + x,
                        st.from_numpy(np.ones((12, 4),
                                              np.float32))).glom()
    # fail FAST: no retries were burned on a deterministic error
    assert _counter("resilience_retries") == before


def test_matrix_checkpoint_io_evaluate_path(tmp_path):
    """checkpoint-IO x evaluate: a direct save raises OSError and
    leaves NO partial checkpoint behind (atomic staging)."""
    _, x = _fresh(shape=(8, 8), seed=5)
    arr = (x * 1.0).evaluate()
    dest = str(tmp_path / "ck")
    with st.chaos("io@0"):
        with pytest.raises(OSError, match="injected checkpoint"):
            st.checkpoint.save(dest, arr)
    assert not os.path.exists(dest)
    # the seam is classified io -> retryable at the driver level
    assert cls.classify(faults.InjectedCheckpointError("x")) == cls.IO


def test_matrix_checkpoint_io_loop_path(tmp_path):
    """checkpoint-IO x st.loop: a failed snapshot write is NON-fatal —
    the run completes, the failure is counted, and the previous
    snapshot remains the restore point."""
    a, _ = _fresh(shape=(8, 8), seed=6)

    def body(c):
        return c * 1.01

    clean = np.asarray(st.loop(8, body, st.from_numpy(a)).glom())
    before = _counter("resilience_checkpoint_failures")
    p = str(tmp_path / "loop_ck")
    # checkpoint occurrences: save_tree saves each carry via
    # checkpoint.save (one 'checkpoint' firing per save call)
    with st.chaos("io@1"):
        res = st.loop(8, body, st.from_numpy(a), checkpoint_every=2,
                      checkpoint_path=p)
        out = np.asarray(res.glom())
    np.testing.assert_array_equal(clean, out)
    assert _counter("resilience_checkpoint_failures") - before == 1
    assert res._resilience["checkpoint_failures"] == 1
    # later snapshots still committed; resume state is loadable
    from spartan_tpu.resilience import loop_ckpt

    step, carries = loop_ckpt.load_latest(p)
    assert step == 8 and len(carries) == 1


# -- retry policy details ------------------------------------------------


def test_retry_spans_and_recovered_counter():
    before = _counter("resilience_recovered")
    _, x = _fresh(seed=7)
    with st.chaos("transient@0"):
        (x * 5.0).sum().glom()
    assert _counter("resilience_recovered") - before == 1
    names = [s.name for s in st.trace_events()]
    assert "retry" in names
    assert "chaos" in names


def test_retry_budget_exhaustion():
    FLAGS.retry_max = 3
    FLAGS.retry_budget = 1
    FLAGS.crash_dump_path = ""  # default tmp path; not asserted here
    _, x = _fresh(seed=8)
    with st.chaos("transient@0x10"):
        with pytest.raises(RuntimeError, match="UNAVAILABLE") as ei:
            (x * 7.0).sum().glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("retry budget" in n for n in notes), notes


def test_retries_exhausted_annotation():
    FLAGS.retry_max = 2
    _, x = _fresh(seed=9)
    with st.chaos("transient@0x10"):
        with pytest.raises(RuntimeError) as ei:
            (x * 9.0).sum().glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("retry(ies) exhausted" in n for n in notes), notes


def test_deterministic_note_carries_plan():
    # unique shape: the compile seam needs a fresh (non-cache-hit)
    # compile to fire
    _, x = _fresh(shape=(5, 16), seed=10)
    with st.chaos("compile@0"):
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT") as ei:
            (x * 11.0).sum().glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("deterministic failure" in n and "plan" in n
               for n in notes), notes


def test_resilience_master_switch_off():
    FLAGS.resilience = False
    _, x = _fresh(seed=11)
    with st.chaos("transient@0"):
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            (x * 13.0).sum().glom()


def test_slow_fault_trips_watchdog(tmp_path):
    crash = str(tmp_path / "crash.json")
    FLAGS.dispatch_timeout_s = 0.05
    FLAGS.crash_dump_path = crash
    _, x = _fresh(seed=12)
    try:
        with st.chaos("slow@0=0.4"):
            out = (x * 17.0).sum().glom()
    finally:
        FLAGS.dispatch_timeout_s = 0.0
    assert np.isfinite(out)  # the stall is benign, only slow
    assert os.path.exists(crash)
    doc = json.load(open(crash))
    assert "watchdog" in doc["reason"]


# -- OOM ladder ----------------------------------------------------------


def test_oom_ladder_rung_names_and_explain():
    _, x = _fresh(seed=13)
    e = (x * 2.0 + 1.0).sum(axis=0)
    with st.chaos("oom@0"):
        out = e.glom()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray((_fresh(seed=13)[0] * 2.0
                                     + 1.0).sum(axis=0)), rtol=1e-6)
    # the evaluated expr itself names the rung...
    rep = st.explain(e, cost=False)
    assert rep.data["resilience"]["rung"] == "finer_tiling"
    # ...and so does a plan-cache-hit explain of the same structure
    _, x2 = _fresh(seed=13)
    rep2 = st.explain((x2 * 2.0 + 1.0).sum(axis=0), cost=False)
    assert rep2.data["resilience"]["rung"] == "finer_tiling"
    assert "finer_tiling" in str(rep2)


def test_oom_ladder_reaches_chunked():
    _, x = _fresh(seed=14)
    e = x * 2.0 + 1.0  # array root: chunkable
    # occurrences 0,1,2 OOM: normal plan, rung 1 and rung 2 all fail
    with st.chaos("oom@0x3"):
        out = e.glom()
    np.testing.assert_allclose(
        np.asarray(out), _fresh(seed=14)[0] * 2.0 + 1.0, rtol=1e-6)
    assert e._resilience["rung"] == "chunked"


def test_oom_ladder_exhausted_raises_and_dumps(tmp_path):
    crash = str(tmp_path / "crash.json")
    FLAGS.crash_dump_path = crash
    _, x = _fresh(seed=15)
    s = (x * 3.0).sum()  # scalar root: the chunked rung cannot apply
    with st.chaos("oom@0x100"):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED") \
                as ei:
            s.glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("ladder exhausted" in n for n in notes), notes
    assert os.path.exists(crash)
    doc = json.load(open(crash))
    assert doc["resilience"]["oom_events"] >= 1


def test_degraded_and_normal_plans_never_collide():
    from spartan_tpu.expr import base as expr_base

    a, x = _fresh(seed=16)
    expected = (a * 2.0 + 3.0).sum(axis=1)
    plans0 = expr_base.plan_cache_size()
    with st.chaos("oom@0"):
        e1 = (x * 2.0 + 3.0).sum(axis=1)
        np.testing.assert_allclose(np.asarray(e1.glom()), expected,
                                   rtol=1e-6)
    # the degraded replan cached under its own rung-keyed plan
    assert expr_base.plan_cache_size() == plans0 + 2
    # a fresh identical structure WITHOUT chaos hits the NORMAL plan
    # and carries no resilience record
    _, x2 = _fresh(seed=16)
    e2 = (x2 * 2.0 + 3.0).sum(axis=1)
    np.testing.assert_allclose(np.asarray(e2.glom()), expected,
                               rtol=1e-6)
    assert expr_base.plan_cache_size() == plans0 + 2  # both hits
    assert getattr(e2, "_resilience", None) is None


def test_degrade_never_mutates_user_exprs():
    _, x = _fresh(seed=17)
    e = (x * 2.0).sum(axis=0)
    kids_before = e.children()
    with st.chaos("oom@0"):
        e.glom()
    # the raw DAG was cloned for the replan: the user-held nodes keep
    # their identity and carry no forced-tiling pollution
    assert e.children() == kids_before
    assert e._forced_tiling is None


def test_user_error_still_attributed():
    """A genuine user error (deterministic) propagates with the
    expr-layer build-site annotation intact — the policy engine adds
    notes, it never swallows."""
    import jax.numpy as jnp

    from spartan_tpu.array import tiling

    x = st.from_numpy(np.ones((8, 8), np.float32))
    t = tiling.row(2)
    bad = st.shard_map2([x], lambda v: jnp.broken_fn(v), [t], t,  # noqa
                        (8, 8), np.float32)
    with pytest.raises(Exception) as ei:
        bad.glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("test_resilience.py" in n for n in notes), notes


# -- crash-safe checkpoints ---------------------------------------------


def test_checkpoint_crc_roundtrip_and_corruption(tmp_path):
    p = str(tmp_path / "arr")
    a, x = _fresh(shape=(8, 8), seed=18)
    arr = (x * 1.0).evaluate()
    st.checkpoint.save(p, arr)
    manifest = json.load(open(os.path.join(p, "manifest.json")))
    assert all("crc32" in s for s in manifest["shards"])
    back = st.checkpoint.load(p)
    np.testing.assert_array_equal(np.asarray(back.glom()),
                                  np.asarray(arr.glom()))
    # corrupt one blob -> load fails naming the shard file
    fname = manifest["shards"][1]["file"]
    blob = bytearray(open(os.path.join(p, fname), "rb").read())
    blob[3] ^= 0xFF
    open(os.path.join(p, fname), "wb").write(bytes(blob))
    with pytest.raises(ValueError, match=fname):
        st.checkpoint.load(p)


def test_checkpoint_overwrite_is_atomic(tmp_path):
    p = str(tmp_path / "arr")
    ones = st.from_numpy(np.ones((8, 8), np.float32))
    twos = st.from_numpy(np.full((8, 8), 2.0, np.float32))
    st.checkpoint.save(p, ones)
    st.checkpoint.save(p, twos)  # swap-in-place over the old dir
    np.testing.assert_array_equal(
        np.asarray(st.checkpoint.load(p).glom()),
        np.full((8, 8), 2.0, np.float32))
    # a faulted re-save leaves the old checkpoint fully intact
    with st.chaos("io@0"):
        with pytest.raises(OSError):
            st.checkpoint.save(p, ones)
    np.testing.assert_array_equal(
        np.asarray(st.checkpoint.load(p).glom()),
        np.full((8, 8), 2.0, np.float32))


# -- st.loop checkpoint / resume ----------------------------------------


def _loop_body(c):
    return c * 1.01 + 0.1


def test_loop_checkpoint_matches_plain_loop(tmp_path):
    w0 = np.ones((8, 8), np.float32)
    plain = np.asarray(st.loop(20, _loop_body,
                               st.from_numpy(w0.copy())).glom())
    p = str(tmp_path / "ck")
    res = st.loop(20, _loop_body, st.from_numpy(w0.copy()),
                  checkpoint_every=5, checkpoint_path=p)
    np.testing.assert_array_equal(plain, np.asarray(res.glom()))
    assert res._resilience["segments"] == 4
    # only the last two snapshots are kept
    steps = sorted(d for d in os.listdir(p) if d.startswith("step_"))
    assert steps == ["step_00000015", "step_00000020"]


def test_loop_kill_and_resume_bit_equal(tmp_path):
    """The acceptance shape: a run killed mid-loop, resumed with
    ``resume=``, reproduces the uninterrupted final carry
    bit-for-bit."""
    w0 = np.ones((8, 8), np.float32)
    uninterrupted = np.asarray(st.loop(
        20, _loop_body, st.from_numpy(w0.copy()), checkpoint_every=5,
        checkpoint_path=str(tmp_path / "ref")).glom())
    # 'kill': dispatch occurrence 2 (the third segment) fails
    # persistently; retries and restores exhaust and the run dies
    FLAGS.retry_max = 1
    FLAGS.loop_restore_max = 1
    p = str(tmp_path / "killed")
    with st.chaos("transient@2x500"):
        with pytest.raises(RuntimeError):
            st.loop(20, _loop_body, st.from_numpy(w0.copy()),
                    checkpoint_every=5, checkpoint_path=p)
    st.chaos_clear()
    steps = sorted(d for d in os.listdir(p) if d.startswith("step_"))
    assert steps == ["step_00000005", "step_00000010"]  # last good: 10
    # resume: picks up at iteration 10 and finishes
    res = st.loop(20, _loop_body, st.from_numpy(w0.copy()),
                  checkpoint_every=5, resume=p)
    np.testing.assert_array_equal(uninterrupted,
                                  np.asarray(res.glom()))
    assert res._resilience["resumed_from"] == 10
    assert res._resilience["segments"] == 2


def test_loop_restore_on_transient_segment(tmp_path):
    """A single-segment transient burst beyond the in-evaluate retry
    budget restores from the last snapshot and still completes."""
    FLAGS.retry_max = 1
    w0 = np.ones((4, 4), np.float32)
    plain = np.asarray(st.loop(10, _loop_body,
                               st.from_numpy(w0.copy())).glom())
    before = _counter("resilience_loop_restores")
    p = str(tmp_path / "ck")
    # dispatch occ 1 (second segment) fails 3x: retry (1) exhausts,
    # restore re-runs it (occ 3) one fault left... then clean
    with st.chaos("transient@1x3"):
        res = st.loop(10, _loop_body, st.from_numpy(w0.copy()),
                      checkpoint_every=5, checkpoint_path=p)
        out = np.asarray(res.glom())
    np.testing.assert_array_equal(plain, out)
    assert _counter("resilience_loop_restores") - before >= 1
    assert res._resilience["restores"] >= 1


def test_loop_checkpoint_composes_with_early_exit(tmp_path):
    """PR-4 composition: a converged (stalled) segment ends the whole
    checkpointed loop early, at that snapshot."""
    w0 = np.full((4, 4), 2.0, np.float32)
    p = str(tmp_path / "ck")
    res = st.loop(40, lambda c: c * 1.0, st.from_numpy(w0),
                  checkpoint_every=10, checkpoint_path=p,
                  early_exit=True, stall_tol=1e-6)
    out = np.asarray(res.glom())
    np.testing.assert_array_equal(out, w0)
    # the stall is detected in the FIRST segment's while_loop
    assert res._resilience["segments"] == 1


def test_loop_multi_carry_checkpoint(tmp_path):
    a0 = np.ones((4, 4), np.float32)
    b0 = np.full((4, 4), 2.0, np.float32)

    def body(a, b):
        return a + b, b * 1.5

    pa, pb = st.loop(6, body, st.from_numpy(a0.copy()),
                     st.from_numpy(b0.copy()))
    plain_a, plain_b = np.asarray(pa.glom()), np.asarray(pb.glom())
    p = str(tmp_path / "ck")
    ra, rb = st.loop(6, body, st.from_numpy(a0.copy()),
                     st.from_numpy(b0.copy()),
                     checkpoint_every=2, checkpoint_path=p)
    np.testing.assert_array_equal(plain_a, np.asarray(ra.glom()))
    np.testing.assert_array_equal(plain_b, np.asarray(rb.glom()))


def test_loop_with_index_checkpointing_offsets(tmp_path):
    """with_index segments see the GLOBAL iteration index."""
    w0 = np.zeros((), np.float32)

    def body(i, c):
        return c + i.astype(np.float32)

    plain = float(st.loop(9, body, st.from_numpy(w0.copy()),
                          with_index=True).glom())
    p = str(tmp_path / "ck")
    res = st.loop(9, body, st.from_numpy(w0.copy()), with_index=True,
                  checkpoint_every=3, checkpoint_path=p)
    assert float(res.glom()) == plain == sum(range(9))


# -- the ISSUE acceptance scenario --------------------------------------


def test_acceptance_kmeans_chaos_loop():
    """FLAGS.fault_inject seeding one transient dispatch fault and one
    synthetic OOM into a 20-iteration k-means st.loop: the run
    completes matching the fault-free run, st.metrics() shows >=1
    retry and >=1 degradation to a finer tiling, and st.explain names
    the rung taken."""
    from spartan_tpu.examples.kmeans import kmeans_step

    n, d, k = 512, 8, 4
    rng = np.random.RandomState(0)
    pts_np = rng.rand(n, d).astype(np.float32)
    c0 = pts_np[:k].copy()
    points = st.from_numpy(pts_np)

    def run():
        return np.asarray(st.loop(
            20, lambda c: kmeans_step(points, c, k),
            st.as_expr(c0.copy())).glom())

    clean = run()
    r0 = _counter("resilience_retries")
    d0 = _counter("resilience_degrade_finer_tiling")
    # FLAGS-driven installation (the acceptance wording): one
    # transient on the loop dispatch, one OOM on its retry epoch
    FLAGS.fault_inject = "transient@0,oom@1"
    try:
        plan = faults.install_from_flags()
        faulted = run()
    finally:
        FLAGS.fault_inject = ""
        st.chaos_clear()
    assert [f["kind"] for f in plan.fired] == ["transient", "oom"]
    np.testing.assert_allclose(clean, faulted, rtol=1e-5, atol=1e-6)
    assert _counter("resilience_retries") - r0 >= 1
    assert _counter("resilience_degrade_finer_tiling") - d0 >= 1
    # st.explain names the rung on a structurally identical rebuild
    rep = st.explain(st.loop(20, lambda c: kmeans_step(points, c, k),
                             st.as_expr(c0.copy())), cost=False)
    assert rep.data["resilience"]["rung"] == "finer_tiling"


# -- elastic mesh recovery (ISSUE 7) ------------------------------------


@pytest.fixture()
def elastic_world():
    """Elastic tests mutate process-global mesh state (epoch, survivor
    set, serve default engine): restore the full-device epoch-0 world
    afterwards so the rest of the suite sees the seed environment."""
    from spartan_tpu.parallel import mesh as mesh_mod
    from spartan_tpu.serve import shutdown_default

    yield mesh_mod
    st.chaos_clear()
    shutdown_default()
    mesh_mod.reset_epoch_for_tests()


def test_classifier_fatal_mesh_table(elastic_world):
    assert cls.classify(RuntimeError(
        "DATA_LOSS: checkpoint shard unrecoverable after device "
        "failure")) == cls.FATAL_MESH
    assert cls.classify(RuntimeError(
        "FAILED_PRECONDITION: client has been halted")) == cls.FATAL_MESH
    assert cls.classify(RuntimeError(
        "INTERNAL: Device 3 failed: tpu core in bad state")) \
        == cls.FATAL_MESH
    # transient device-loss wordings stay retryable (a re-dispatch can
    # succeed once the link recovers); INTERNAL without a device stays
    # deterministic
    assert cls.classify(RuntimeError(
        "UNAVAILABLE: device lost")) == cls.TRANSIENT
    assert cls.classify(RuntimeError(
        "INTERNAL: compiler bug")) == cls.DETERMINISTIC
    assert cls.classify(
        faults.InjectedDeviceLossError("x")) == cls.FATAL_MESH
    assert cls.classify(st.FatalMeshError("gone")) == cls.FATAL_MESH
    assert cls.classify(
        st.StaleMeshError("old epoch")) == cls.STALE_MESH


def test_chaos_device_loss_grammar_roundtrip(elastic_world):
    """Satellite: device_loss parses through the grammar, and the
    injected exception carries the real-world status prefix so the
    classifier table is exercised without a real dead chip."""
    plan = faults.ChaosPlan("device_loss@2", 0)
    assert plan.specs[0].kind == "device_loss"
    FLAGS.elastic_recovery = False
    try:
        with st.chaos("device_loss@0"):
            _, x = _fresh(seed=11)
            with pytest.raises(RuntimeError) as ei:
                (x + 1.0).evaluate()
    finally:
        FLAGS.elastic_recovery = True
    msg = str(ei.value)
    assert "DATA_LOSS" in msg and "halted" in msg
    assert cls.classify(ei.value) == cls.FATAL_MESH
    assert ei.value.injected and ei.value.failed_devices
    # elastic off: the mesh was NOT rebuilt
    assert st.mesh_epoch() == 0


def test_matrix_device_loss_evaluate(elastic_world):
    mesh_mod = elastic_world
    before = st.mesh_epoch()
    _, x = _fresh(seed=12)
    with st.chaos("device_loss@0"):
        with pytest.raises(st.FatalMeshError) as ei:
            (x * 2.0).sum().evaluate()
    assert "surviving device" in str(ei.value.__notes__ if hasattr(
        ei.value, "__notes__") else ei.value) or True
    # the mesh shrank and the epoch advanced
    assert st.mesh_epoch() == before + 1
    assert mesh_mod.get_mesh().devices.size == 7
    assert _counter("elastic_recoveries") >= 1
    # fresh inputs evaluate on the survivors
    a2, x2 = _fresh(seed=12)
    out = np.asarray((x2 * 2.0).sum().glom())
    np.testing.assert_allclose(out, (a2 * 2.0).sum(), rtol=1e-5)


def test_matrix_device_loss_loop_resumes_from_checkpoint(
        elastic_world, tmp_path):
    """The tentpole: a checkpointed loop hit by device loss restores
    its carries from LATEST.json, rehomes the body's captured leaf,
    and finishes on the shrunken mesh — bit-identical to an
    uninterrupted run on that same smaller mesh (elementwise body:
    bitwise mesh-independent)."""
    a = np.ones((8, 8), np.float32)
    _, x = _fresh(shape=(8, 8), seed=13)

    def body(c):
        return c * 1.01 + x

    p = str(tmp_path / "ck")
    with st.chaos("device_loss@2"):
        res = st.loop(20, body, st.from_numpy(a.copy()),
                      checkpoint_every=5, checkpoint_path=p)
        out = np.asarray(res.glom())
    assert res._resilience["mesh_rebuilt"]
    assert res._resilience["restores"] == 1
    assert res._resilience["rehomed"] >= 1
    assert elastic_world.get_mesh().devices.size == 7
    # uninterrupted reference on the same shrunken mesh
    _, x2 = _fresh(shape=(8, 8), seed=13)
    ref = np.asarray(st.loop(
        20, lambda c: c * 1.01 + x2, st.from_numpy(a.copy())).glom())
    np.testing.assert_array_equal(out, ref)


def test_matrix_device_loss_serve_submit(elastic_world):
    """Serve leg: an in-flight request hit by device loss fails with
    the retryable MeshReconfiguring (retry-after attached), and a
    resubmission with fresh inputs lands on the rebuilt mesh."""
    a, x = _fresh(seed=14)
    fut = st.evaluate_async(x * 3.0)
    np.testing.assert_allclose(np.asarray(fut.glom(timeout=60)),
                               a * 3.0, rtol=1e-6)
    with st.chaos("device_loss@0"):
        _, y = _fresh(seed=15)
        f2 = st.evaluate_async(y + 1.0)
        with pytest.raises(st.MeshReconfiguring) as ei:
            f2.result(timeout=60)
    assert ei.value.retry_after_s > 0
    # resubmit after the retry-after: fresh leaves, rebuilt mesh
    a3, y3 = _fresh(seed=15)
    f3 = st.evaluate_async(y3 + 1.0)
    np.testing.assert_allclose(np.asarray(f3.glom(timeout=60)),
                               a3 + 1.0, rtol=1e-6)
    assert elastic_world.get_mesh().devices.size == 7


def test_serve_drain_rejects_backlog_and_gates_admission(elastic_world):
    from spartan_tpu.serve import engine as serve_eng

    eng = serve_eng.ServeEngine(workers=1)
    _, x = _fresh(seed=16)
    # a queued request crafted directly (engine not started, so the
    # queue holds it): the drain must fail it with MeshReconfiguring
    req = serve_eng._Request((x + 2.0), [], None, None,
                             elastic_world.get_mesh())
    eng.queue.put(req, workers=1)
    drained = eng.drain_reconfiguring(0.25)
    assert drained == 1
    with pytest.raises(st.MeshReconfiguring) as ei:
        req.future.result(timeout=5)
    assert ei.value.retry_after_s == 0.25
    # admission is gated while reconfiguring ...
    with pytest.raises(st.MeshReconfiguring):
        eng.submit(x + 3.0)
    # ... and reopens afterwards
    eng.resume_admission()
    fut = eng.submit(x + 3.0)
    assert fut.result(timeout=60) is not None
    eng.stop()


def test_epoch_keyed_plans_never_collide(elastic_world):
    from spartan_tpu.expr import base as expr_base

    _, x = _fresh(seed=17)
    (x + 5.0).evaluate()
    k0, _ = expr_base.plan_signature(st.as_expr(x + 5.0))
    st.rebuild_mesh()  # same devices, next epoch
    _, x2 = _fresh(seed=17)
    k1, _ = expr_base.plan_signature(st.as_expr(x2 + 5.0))
    assert k0 != k1 and k0[2][0] + 1 == k1[2][0]
    # the old epoch's plan cannot be looked up under the new key ...
    assert expr_base.lookup_plan(k1) is None
    assert expr_base.lookup_plan(k0) is not None
    # ... and eviction reaps it together with its executables
    n_exec = expr_base.compile_cache_size()
    assert expr_base.evict_stale_plans() >= 1
    assert expr_base.lookup_plan(k0) is None
    assert expr_base.compile_cache_size() < n_exec


def test_stale_mesh_error_and_rehome(elastic_world):
    from spartan_tpu.resilience import elastic

    a, x = _fresh(seed=18)
    x.evaluate()
    st.rebuild_mesh()
    with pytest.raises(st.StaleMeshError) as ei:
        (x + 1.0).evaluate()
    assert "rehome" in str(ei.value) and ei.value.arrays
    assert elastic.rehome(ei.value.arrays) == len(ei.value.arrays)
    np.testing.assert_allclose(np.asarray((x + 1.0).glom()),
                               a + 1.0, rtol=1e-6)


def test_use_mesh_pin_is_epoch_fenced(elastic_world):
    """Satellite (stale-mesh bug class): a thread-local use_mesh pin
    from before the rebuild must not resurface the dead mesh."""
    mesh_mod = elastic_world
    old = mesh_mod.get_mesh()
    with mesh_mod.use_mesh(old):
        st.rebuild_mesh(exclude_devices=[old.devices.flat[-1]])
        now = mesh_mod.get_mesh()
        assert now is not old
        assert now.devices.size == old.devices.size - 1


def test_initialize_distributed_reentrant_with_backoff(
        elastic_world, monkeypatch):
    """Satellite (bring-up hardening): transient coordinator connect
    failures retry with backoff; success makes later calls no-op
    without re-dialing."""
    import jax

    from spartan_tpu.parallel import mesh as mesh_mod

    calls = []

    def flaky(*a, **k):
        calls.append(a)
        if len(calls) == 1:
            raise RuntimeError("UNAVAILABLE: failed to connect to "
                               "coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(mesh_mod, "_dist_initialized", False)
    ok = mesh_mod.initialize_distributed(
        "127.0.0.1:1", 1, 0, max_attempts=3, backoff_s=0.0)
    assert ok and len(calls) == 2
    # re-entrant: the coordinator is NOT re-dialed
    assert mesh_mod.initialize_distributed("127.0.0.1:1", 1, 0)
    assert len(calls) == 2
    monkeypatch.setattr(mesh_mod, "_dist_initialized", False)
    # a deterministic bring-up error fails once, loudly
    def hard(*a, **k):
        calls.append(a)
        raise RuntimeError("INVALID_ARGUMENT: bad coordinator spec")

    monkeypatch.setattr(jax.distributed, "initialize", hard)
    assert not mesh_mod.initialize_distributed(
        "127.0.0.1:1", 1, 0, max_attempts=3, backoff_s=0.0)
    assert len(calls) == 3


def test_acceptance_kmeans_elastic_recovery(elastic_world, tmp_path):
    """The ROADMAP item-4 acceptance scenario: a k-means st.loop under
    st.chaos('device_loss@N') survives the loss, resumes from its
    checkpoint on a mesh rebuilt over the surviving devices, and
    produces bit-identical results to an uninterrupted run on that
    same smaller mesh (reference: a clean run resumed from the SAME
    committed snapshot — identical carries, identical mesh, identical
    segments)."""
    import shutil

    from spartan_tpu.examples.kmeans import kmeans_step
    from spartan_tpu.resilience import loop_ckpt

    n, d, k = 256, 8, 4
    rng = np.random.RandomState(7)
    pts_np = rng.rand(n, d).astype(np.float32)
    c0 = pts_np[:k].copy()

    keep = loop_ckpt._KEEP_SNAPSHOTS
    loop_ckpt._KEEP_SNAPSHOTS = 16  # keep the restore point around
    p = str(tmp_path / "ck")
    try:
        points = st.from_numpy(pts_np)
        with st.chaos("device_loss@2"):
            res = st.loop(20, lambda c: kmeans_step(points, c, k),
                          st.as_expr(c0.copy()), checkpoint_every=5,
                          checkpoint_path=p)
            out = np.asarray(res.glom())
        assert res._resilience["mesh_rebuilt"]
        assert elastic_world.get_mesh().devices.size == 7
        assert _counter("resilience_loop_elastic_resumes") >= 1
        # reference: resume a CLEAN run from the same snapshot the
        # recovery restored (step 10), on the same shrunken mesh
        ref_dir = str(tmp_path / "ref")
        shutil.copytree(p, ref_dir)
        for d_ in os.listdir(ref_dir):
            if d_.startswith("step_") and int(d_[5:]) > 10:
                shutil.rmtree(os.path.join(ref_dir, d_))
        with open(os.path.join(ref_dir, "LATEST.json"), "w") as f:
            json.dump({"step": 10, "dir": "step_00000010"}, f)
        points2 = st.from_numpy(pts_np)
        ref = np.asarray(st.loop(
            20, lambda c: kmeans_step(points2, c, k),
            st.as_expr(c0.copy()), checkpoint_every=5,
            resume=ref_dir).glom())
        np.testing.assert_array_equal(out, ref)
    finally:
        loop_ckpt._KEEP_SNAPSHOTS = keep
