"""Resilient execution (ISSUE 5): fault injection at the real seams,
classified retry, OOM degradation ladder, crash-safe checkpoints, and
st.loop checkpoint/resume — the full fault matrix
{transient, deterministic, OOM, checkpoint-IO} x {evaluate, st.loop},
exercised deterministically on CPU via ``st.chaos``."""

import json
import os

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.resilience import classify as cls
from spartan_tpu.resilience import engine, faults
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _setup(mesh2d):
    saved = {n: getattr(FLAGS, n) for n in (
        "retry_backoff_s", "retry_max", "retry_budget", "oom_degrade",
        "crash_dump_path", "dispatch_timeout_s", "resilience",
        "loop_restore_max", "opt_map_fusion", "opt_reduce_fusion")}
    FLAGS.retry_backoff_s = 0.0
    engine.reset()
    st.chaos_clear()
    yield
    st.chaos_clear()
    engine.reset()
    for n, v in saved.items():
        setattr(FLAGS, n, v)


def _counter(name):
    return st.metrics()["counters"].get(name, 0)


def _fresh(shape=(16, 16), seed=0, scale=1.0):
    rng = np.random.RandomState(seed)
    a = (rng.rand(*shape).astype(np.float32) + 1.0) * scale
    return a, st.from_numpy(a)


# -- classifier ----------------------------------------------------------


def test_classifier_table():
    assert cls.classify(RuntimeError(
        "UNAVAILABLE: socket closed")) == cls.TRANSIENT
    assert cls.classify(RuntimeError(
        "DEADLINE_EXCEEDED: operation timed out")) == cls.TRANSIENT
    assert cls.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes")) == cls.OOM
    assert cls.classify(MemoryError()) == cls.OOM
    assert cls.classify(OSError("disk full")) == cls.IO
    assert cls.classify(ValueError("bad axis")) == cls.DETERMINISTIC
    assert cls.classify(RuntimeError(
        "INVALID_ARGUMENT: bad layout")) == cls.DETERMINISTIC
    # XLA INTERNAL errors are deliberately NOT transient
    assert cls.classify(RuntimeError(
        "INTERNAL: compiler bug")) == cls.DETERMINISTIC
    # injected faults classify like their real counterparts
    assert cls.classify(
        faults.InjectedTransientError("x")) == cls.TRANSIENT
    assert cls.classify(faults.InjectedOOMError("x")) == cls.OOM
    assert cls.classify(
        faults.InjectedCompileError("x")) == cls.DETERMINISTIC
    assert cls.classify(
        faults.InjectedCheckpointError("x")) == cls.IO


def test_chaos_spec_parsing():
    plan = faults.ChaosPlan("transient@2,oom@4x3,slow@1=0.25,io@0", 7)
    kinds = [s.kind for s in plan.specs]
    assert kinds == ["transient", "oom", "slow", "io"]
    assert plan.specs[1].at == 4 and plan.specs[1].count == 3
    assert plan.specs[2].dur == 0.25
    with pytest.raises(ValueError, match="bad fault token"):
        faults.ChaosPlan("explode@1", 0)
    with pytest.raises(ValueError, match="needs a deterministic"):
        faults.ChaosPlan("transient", 0)


def test_chaos_probabilistic_is_seed_deterministic():
    a = faults.FaultSpec("transient:0.3")
    hits1 = [a.hits(i, 42) for i in range(64)]
    hits2 = [a.hits(i, 42) for i in range(64)]
    hits3 = [a.hits(i, 43) for i in range(64)]
    assert hits1 == hits2  # same seed -> same fault sequence
    assert hits1 != hits3  # different seed -> different sequence
    assert 2 < sum(hits1) < 40  # roughly p=0.3


# -- fault matrix: {transient, oom, deterministic} x {evaluate, loop} ----


def _run_case(mode, spec):
    """Build a fresh structure, run it fault-free, then run an
    identical structure under ``spec``; return (clean, faulted)."""
    if mode == "evaluate":
        a, x = _fresh(seed=3)
        clean = np.asarray(((x * 2.0 + 1.0).sum(axis=0)).glom())
        with st.chaos(spec):
            a2, x2 = _fresh(seed=3)
            faulted = np.asarray(((x2 * 2.0 + 1.0).sum(axis=0)).glom())
        return clean, faulted
    a, x = _fresh(shape=(8, 8), seed=4)

    def body(c):
        return c * 1.01 + x

    clean = np.asarray(st.loop(5, body, st.from_numpy(a)).glom())
    with st.chaos(spec):
        faulted = np.asarray(st.loop(5, body, st.from_numpy(a)).glom())
    return clean, faulted


@pytest.mark.parametrize("mode", ["evaluate", "loop"])
def test_matrix_transient_recovers(mode):
    before = _counter("resilience_retries")
    clean, faulted = _run_case(mode, "transient@0")
    assert _counter("resilience_retries") - before >= 1
    np.testing.assert_array_equal(clean, faulted)


@pytest.mark.parametrize("mode", ["evaluate", "loop"])
def test_matrix_oom_degrades(mode):
    before = _counter("resilience_degrades")
    clean, faulted = _run_case(mode, "oom@0")
    assert _counter("resilience_degrades") - before >= 1
    np.testing.assert_allclose(clean, faulted, rtol=1e-6)


@pytest.mark.parametrize("mode", ["evaluate", "loop"])
def test_matrix_deterministic_fails_fast(mode):
    # compile-site faults fire only on a FRESH compile, so these
    # structures use shapes no other test compiles (a cache hit would
    # skip the seam — which is itself the right production behavior)
    before = _counter("resilience_retries")
    with st.chaos("compile@0"):
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
            if mode == "evaluate":
                _, x = _fresh(shape=(24, 8), seed=20)
                (x * 2.0 + 1.0).sum(axis=0).glom()
            else:
                _, x = _fresh(shape=(12, 4), seed=21)
                st.loop(5, lambda c: c * 1.5 + x,
                        st.from_numpy(np.ones((12, 4),
                                              np.float32))).glom()
    # fail FAST: no retries were burned on a deterministic error
    assert _counter("resilience_retries") == before


def test_matrix_checkpoint_io_evaluate_path(tmp_path):
    """checkpoint-IO x evaluate: a direct save raises OSError and
    leaves NO partial checkpoint behind (atomic staging)."""
    _, x = _fresh(shape=(8, 8), seed=5)
    arr = (x * 1.0).evaluate()
    dest = str(tmp_path / "ck")
    with st.chaos("io@0"):
        with pytest.raises(OSError, match="injected checkpoint"):
            st.checkpoint.save(dest, arr)
    assert not os.path.exists(dest)
    # the seam is classified io -> retryable at the driver level
    assert cls.classify(faults.InjectedCheckpointError("x")) == cls.IO


def test_matrix_checkpoint_io_loop_path(tmp_path):
    """checkpoint-IO x st.loop: a failed snapshot write is NON-fatal —
    the run completes, the failure is counted, and the previous
    snapshot remains the restore point."""
    a, _ = _fresh(shape=(8, 8), seed=6)

    def body(c):
        return c * 1.01

    clean = np.asarray(st.loop(8, body, st.from_numpy(a)).glom())
    before = _counter("resilience_checkpoint_failures")
    p = str(tmp_path / "loop_ck")
    # checkpoint occurrences: save_tree saves each carry via
    # checkpoint.save (one 'checkpoint' firing per save call)
    with st.chaos("io@1"):
        res = st.loop(8, body, st.from_numpy(a), checkpoint_every=2,
                      checkpoint_path=p)
        out = np.asarray(res.glom())
    np.testing.assert_array_equal(clean, out)
    assert _counter("resilience_checkpoint_failures") - before == 1
    assert res._resilience["checkpoint_failures"] == 1
    # later snapshots still committed; resume state is loadable
    from spartan_tpu.resilience import loop_ckpt

    step, carries = loop_ckpt.load_latest(p)
    assert step == 8 and len(carries) == 1


# -- retry policy details ------------------------------------------------


def test_retry_spans_and_recovered_counter():
    before = _counter("resilience_recovered")
    _, x = _fresh(seed=7)
    with st.chaos("transient@0"):
        (x * 5.0).sum().glom()
    assert _counter("resilience_recovered") - before == 1
    names = [s.name for s in st.trace_events()]
    assert "retry" in names
    assert "chaos" in names


def test_retry_budget_exhaustion():
    FLAGS.retry_max = 3
    FLAGS.retry_budget = 1
    FLAGS.crash_dump_path = ""  # default tmp path; not asserted here
    _, x = _fresh(seed=8)
    with st.chaos("transient@0x10"):
        with pytest.raises(RuntimeError, match="UNAVAILABLE") as ei:
            (x * 7.0).sum().glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("retry budget" in n for n in notes), notes


def test_retries_exhausted_annotation():
    FLAGS.retry_max = 2
    _, x = _fresh(seed=9)
    with st.chaos("transient@0x10"):
        with pytest.raises(RuntimeError) as ei:
            (x * 9.0).sum().glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("retry(ies) exhausted" in n for n in notes), notes


def test_deterministic_note_carries_plan():
    # unique shape: the compile seam needs a fresh (non-cache-hit)
    # compile to fire
    _, x = _fresh(shape=(5, 16), seed=10)
    with st.chaos("compile@0"):
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT") as ei:
            (x * 11.0).sum().glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("deterministic failure" in n and "plan" in n
               for n in notes), notes


def test_resilience_master_switch_off():
    FLAGS.resilience = False
    _, x = _fresh(seed=11)
    with st.chaos("transient@0"):
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            (x * 13.0).sum().glom()


def test_slow_fault_trips_watchdog(tmp_path):
    crash = str(tmp_path / "crash.json")
    FLAGS.dispatch_timeout_s = 0.05
    FLAGS.crash_dump_path = crash
    _, x = _fresh(seed=12)
    try:
        with st.chaos("slow@0=0.4"):
            out = (x * 17.0).sum().glom()
    finally:
        FLAGS.dispatch_timeout_s = 0.0
    assert np.isfinite(out)  # the stall is benign, only slow
    assert os.path.exists(crash)
    doc = json.load(open(crash))
    assert "watchdog" in doc["reason"]


# -- OOM ladder ----------------------------------------------------------


def test_oom_ladder_rung_names_and_explain():
    _, x = _fresh(seed=13)
    e = (x * 2.0 + 1.0).sum(axis=0)
    with st.chaos("oom@0"):
        out = e.glom()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray((_fresh(seed=13)[0] * 2.0
                                     + 1.0).sum(axis=0)), rtol=1e-6)
    # the evaluated expr itself names the rung...
    rep = st.explain(e, cost=False)
    assert rep.data["resilience"]["rung"] == "finer_tiling"
    # ...and so does a plan-cache-hit explain of the same structure
    _, x2 = _fresh(seed=13)
    rep2 = st.explain((x2 * 2.0 + 1.0).sum(axis=0), cost=False)
    assert rep2.data["resilience"]["rung"] == "finer_tiling"
    assert "finer_tiling" in str(rep2)


def test_oom_ladder_reaches_chunked():
    _, x = _fresh(seed=14)
    e = x * 2.0 + 1.0  # array root: chunkable
    # occurrences 0,1,2 OOM: normal plan, rung 1 and rung 2 all fail
    with st.chaos("oom@0x3"):
        out = e.glom()
    np.testing.assert_allclose(
        np.asarray(out), _fresh(seed=14)[0] * 2.0 + 1.0, rtol=1e-6)
    assert e._resilience["rung"] == "chunked"


def test_oom_ladder_exhausted_raises_and_dumps(tmp_path):
    crash = str(tmp_path / "crash.json")
    FLAGS.crash_dump_path = crash
    _, x = _fresh(seed=15)
    s = (x * 3.0).sum()  # scalar root: the chunked rung cannot apply
    with st.chaos("oom@0x100"):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED") \
                as ei:
            s.glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("ladder exhausted" in n for n in notes), notes
    assert os.path.exists(crash)
    doc = json.load(open(crash))
    assert doc["resilience"]["oom_events"] >= 1


def test_degraded_and_normal_plans_never_collide():
    from spartan_tpu.expr import base as expr_base

    a, x = _fresh(seed=16)
    expected = (a * 2.0 + 3.0).sum(axis=1)
    plans0 = expr_base.plan_cache_size()
    with st.chaos("oom@0"):
        e1 = (x * 2.0 + 3.0).sum(axis=1)
        np.testing.assert_allclose(np.asarray(e1.glom()), expected,
                                   rtol=1e-6)
    # the degraded replan cached under its own rung-keyed plan
    assert expr_base.plan_cache_size() == plans0 + 2
    # a fresh identical structure WITHOUT chaos hits the NORMAL plan
    # and carries no resilience record
    _, x2 = _fresh(seed=16)
    e2 = (x2 * 2.0 + 3.0).sum(axis=1)
    np.testing.assert_allclose(np.asarray(e2.glom()), expected,
                               rtol=1e-6)
    assert expr_base.plan_cache_size() == plans0 + 2  # both hits
    assert getattr(e2, "_resilience", None) is None


def test_degrade_never_mutates_user_exprs():
    _, x = _fresh(seed=17)
    e = (x * 2.0).sum(axis=0)
    kids_before = e.children()
    with st.chaos("oom@0"):
        e.glom()
    # the raw DAG was cloned for the replan: the user-held nodes keep
    # their identity and carry no forced-tiling pollution
    assert e.children() == kids_before
    assert e._forced_tiling is None


def test_user_error_still_attributed():
    """A genuine user error (deterministic) propagates with the
    expr-layer build-site annotation intact — the policy engine adds
    notes, it never swallows."""
    import jax.numpy as jnp

    from spartan_tpu.array import tiling

    x = st.from_numpy(np.ones((8, 8), np.float32))
    t = tiling.row(2)
    bad = st.shard_map2([x], lambda v: jnp.broken_fn(v), [t], t,  # noqa
                        (8, 8), np.float32)
    with pytest.raises(Exception) as ei:
        bad.glom()
    notes = getattr(ei.value, "__notes__", [])
    assert any("test_resilience.py" in n for n in notes), notes


# -- crash-safe checkpoints ---------------------------------------------


def test_checkpoint_crc_roundtrip_and_corruption(tmp_path):
    p = str(tmp_path / "arr")
    a, x = _fresh(shape=(8, 8), seed=18)
    arr = (x * 1.0).evaluate()
    st.checkpoint.save(p, arr)
    manifest = json.load(open(os.path.join(p, "manifest.json")))
    assert all("crc32" in s for s in manifest["shards"])
    back = st.checkpoint.load(p)
    np.testing.assert_array_equal(np.asarray(back.glom()),
                                  np.asarray(arr.glom()))
    # corrupt one blob -> load fails naming the shard file
    fname = manifest["shards"][1]["file"]
    blob = bytearray(open(os.path.join(p, fname), "rb").read())
    blob[3] ^= 0xFF
    open(os.path.join(p, fname), "wb").write(bytes(blob))
    with pytest.raises(ValueError, match=fname):
        st.checkpoint.load(p)


def test_checkpoint_overwrite_is_atomic(tmp_path):
    p = str(tmp_path / "arr")
    ones = st.from_numpy(np.ones((8, 8), np.float32))
    twos = st.from_numpy(np.full((8, 8), 2.0, np.float32))
    st.checkpoint.save(p, ones)
    st.checkpoint.save(p, twos)  # swap-in-place over the old dir
    np.testing.assert_array_equal(
        np.asarray(st.checkpoint.load(p).glom()),
        np.full((8, 8), 2.0, np.float32))
    # a faulted re-save leaves the old checkpoint fully intact
    with st.chaos("io@0"):
        with pytest.raises(OSError):
            st.checkpoint.save(p, ones)
    np.testing.assert_array_equal(
        np.asarray(st.checkpoint.load(p).glom()),
        np.full((8, 8), 2.0, np.float32))


# -- st.loop checkpoint / resume ----------------------------------------


def _loop_body(c):
    return c * 1.01 + 0.1


def test_loop_checkpoint_matches_plain_loop(tmp_path):
    w0 = np.ones((8, 8), np.float32)
    plain = np.asarray(st.loop(20, _loop_body,
                               st.from_numpy(w0.copy())).glom())
    p = str(tmp_path / "ck")
    res = st.loop(20, _loop_body, st.from_numpy(w0.copy()),
                  checkpoint_every=5, checkpoint_path=p)
    np.testing.assert_array_equal(plain, np.asarray(res.glom()))
    assert res._resilience["segments"] == 4
    # only the last two snapshots are kept
    steps = sorted(d for d in os.listdir(p) if d.startswith("step_"))
    assert steps == ["step_00000015", "step_00000020"]


def test_loop_kill_and_resume_bit_equal(tmp_path):
    """The acceptance shape: a run killed mid-loop, resumed with
    ``resume=``, reproduces the uninterrupted final carry
    bit-for-bit."""
    w0 = np.ones((8, 8), np.float32)
    uninterrupted = np.asarray(st.loop(
        20, _loop_body, st.from_numpy(w0.copy()), checkpoint_every=5,
        checkpoint_path=str(tmp_path / "ref")).glom())
    # 'kill': dispatch occurrence 2 (the third segment) fails
    # persistently; retries and restores exhaust and the run dies
    FLAGS.retry_max = 1
    FLAGS.loop_restore_max = 1
    p = str(tmp_path / "killed")
    with st.chaos("transient@2x500"):
        with pytest.raises(RuntimeError):
            st.loop(20, _loop_body, st.from_numpy(w0.copy()),
                    checkpoint_every=5, checkpoint_path=p)
    st.chaos_clear()
    steps = sorted(d for d in os.listdir(p) if d.startswith("step_"))
    assert steps == ["step_00000005", "step_00000010"]  # last good: 10
    # resume: picks up at iteration 10 and finishes
    res = st.loop(20, _loop_body, st.from_numpy(w0.copy()),
                  checkpoint_every=5, resume=p)
    np.testing.assert_array_equal(uninterrupted,
                                  np.asarray(res.glom()))
    assert res._resilience["resumed_from"] == 10
    assert res._resilience["segments"] == 2


def test_loop_restore_on_transient_segment(tmp_path):
    """A single-segment transient burst beyond the in-evaluate retry
    budget restores from the last snapshot and still completes."""
    FLAGS.retry_max = 1
    w0 = np.ones((4, 4), np.float32)
    plain = np.asarray(st.loop(10, _loop_body,
                               st.from_numpy(w0.copy())).glom())
    before = _counter("resilience_loop_restores")
    p = str(tmp_path / "ck")
    # dispatch occ 1 (second segment) fails 3x: retry (1) exhausts,
    # restore re-runs it (occ 3) one fault left... then clean
    with st.chaos("transient@1x3"):
        res = st.loop(10, _loop_body, st.from_numpy(w0.copy()),
                      checkpoint_every=5, checkpoint_path=p)
        out = np.asarray(res.glom())
    np.testing.assert_array_equal(plain, out)
    assert _counter("resilience_loop_restores") - before >= 1
    assert res._resilience["restores"] >= 1


def test_loop_checkpoint_composes_with_early_exit(tmp_path):
    """PR-4 composition: a converged (stalled) segment ends the whole
    checkpointed loop early, at that snapshot."""
    w0 = np.full((4, 4), 2.0, np.float32)
    p = str(tmp_path / "ck")
    res = st.loop(40, lambda c: c * 1.0, st.from_numpy(w0),
                  checkpoint_every=10, checkpoint_path=p,
                  early_exit=True, stall_tol=1e-6)
    out = np.asarray(res.glom())
    np.testing.assert_array_equal(out, w0)
    # the stall is detected in the FIRST segment's while_loop
    assert res._resilience["segments"] == 1


def test_loop_multi_carry_checkpoint(tmp_path):
    a0 = np.ones((4, 4), np.float32)
    b0 = np.full((4, 4), 2.0, np.float32)

    def body(a, b):
        return a + b, b * 1.5

    pa, pb = st.loop(6, body, st.from_numpy(a0.copy()),
                     st.from_numpy(b0.copy()))
    plain_a, plain_b = np.asarray(pa.glom()), np.asarray(pb.glom())
    p = str(tmp_path / "ck")
    ra, rb = st.loop(6, body, st.from_numpy(a0.copy()),
                     st.from_numpy(b0.copy()),
                     checkpoint_every=2, checkpoint_path=p)
    np.testing.assert_array_equal(plain_a, np.asarray(ra.glom()))
    np.testing.assert_array_equal(plain_b, np.asarray(rb.glom()))


def test_loop_with_index_checkpointing_offsets(tmp_path):
    """with_index segments see the GLOBAL iteration index."""
    w0 = np.zeros((), np.float32)

    def body(i, c):
        return c + i.astype(np.float32)

    plain = float(st.loop(9, body, st.from_numpy(w0.copy()),
                          with_index=True).glom())
    p = str(tmp_path / "ck")
    res = st.loop(9, body, st.from_numpy(w0.copy()), with_index=True,
                  checkpoint_every=3, checkpoint_path=p)
    assert float(res.glom()) == plain == sum(range(9))


# -- the ISSUE acceptance scenario --------------------------------------


def test_acceptance_kmeans_chaos_loop():
    """FLAGS.fault_inject seeding one transient dispatch fault and one
    synthetic OOM into a 20-iteration k-means st.loop: the run
    completes matching the fault-free run, st.metrics() shows >=1
    retry and >=1 degradation to a finer tiling, and st.explain names
    the rung taken."""
    from spartan_tpu.examples.kmeans import kmeans_step

    n, d, k = 512, 8, 4
    rng = np.random.RandomState(0)
    pts_np = rng.rand(n, d).astype(np.float32)
    c0 = pts_np[:k].copy()
    points = st.from_numpy(pts_np)

    def run():
        return np.asarray(st.loop(
            20, lambda c: kmeans_step(points, c, k),
            st.as_expr(c0.copy())).glom())

    clean = run()
    r0 = _counter("resilience_retries")
    d0 = _counter("resilience_degrade_finer_tiling")
    # FLAGS-driven installation (the acceptance wording): one
    # transient on the loop dispatch, one OOM on its retry epoch
    FLAGS.fault_inject = "transient@0,oom@1"
    try:
        plan = faults.install_from_flags()
        faulted = run()
    finally:
        FLAGS.fault_inject = ""
        st.chaos_clear()
    assert [f["kind"] for f in plan.fired] == ["transient", "oom"]
    np.testing.assert_allclose(clean, faulted, rtol=1e-5, atol=1e-6)
    assert _counter("resilience_retries") - r0 >= 1
    assert _counter("resilience_degrade_finer_tiling") - d0 >= 1
    # st.explain names the rung on a structurally identical rebuild
    rep = st.explain(st.loop(20, lambda c: kmeans_step(points, c, k),
                             st.as_expr(c0.copy())), cost=False)
    assert rep.data["resilience"]["rung"] == "finer_tiling"
