"""Predictive memory governor (ISSUE 8): the per-plan peak-HBM model,
predictive rung selection BEFORE the first dispatch (zero reactive OOM
retries, bit-identical to the reactive path), the serve engine's
memory reservation ledger, and the tiling DP's soft memory term —
with the ``oom@`` chaos path proving the REACTIVE ladder stays as the
fallback."""

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.expr import base
from spartan_tpu.expr.base import ValExpr
from spartan_tpu.resilience import degrade
from spartan_tpu.resilience import memory as mem
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _setup(mesh2d):
    saved = {n: getattr(FLAGS, n) for n in (
        "hbm_budget_bytes", "memory_governor", "oom_degrade",
        "retry_backoff_s", "tiling_memory_weight", "serve_workers",
        "serve_batch_window_s")}
    FLAGS.retry_backoff_s = 0.0
    base.clear_compile_cache()
    st.chaos_clear()
    from spartan_tpu.resilience import engine as resilience_engine

    resilience_engine.reset()
    yield
    st.chaos_clear()
    for n, v in saved.items():
        setattr(FLAGS, n, v)
    base.clear_compile_cache()


def _counter(name):
    return st.metrics()["counters"].get(name, 0)


def _plan_for(expr):
    mesh = st.get_mesh()
    plan_key, rctx = base.plan_signature(expr, mesh)
    plan = base.lookup_plan(plan_key)
    if plan is None:
        plan, _dag, _ = base._build_plan(expr, mesh, rctx, plan_key)
    return plan


# -- the model: accuracy vs XLA memory_analysis --------------------------


def _matrix():
    rng = np.random.RandomState(0)
    x = st.from_numpy(rng.rand(1024, 256).astype(np.float32))
    y = st.from_numpy(rng.rand(1024, 256).astype(np.float32))
    a = st.from_numpy(rng.rand(512, 512).astype(np.float32))
    b = st.from_numpy(rng.rand(512, 512).astype(np.float32))
    w = st.from_numpy(rng.rand(512, 512).astype(np.float32))
    return {
        "map": ((x + y) * 3.0 - x, ()),
        "dot": (st.dot(a, b), ()),
        "reduce_axis": ((x * x).sum(axis=0), ()),
        "reduce_all": ((x + y).sum(), ()),
        "loop": (st.loop(10, lambda c: c * 0.5 + a, w), ()),
        "loop_donate": (st.loop(10, lambda c: c * 0.5 + a, w,
                                donate_init=True), (w,)),
    }


def test_estimator_within_25pct_of_xla():
    """The ISSUE-8 accuracy gate: predicted peak within +/-25% of
    ``compiled.memory_analysis()`` across the {map, dot, reduce,
    loop-with-donation} plan matrix (sharded AOT compile on the
    8-virtual-device CPU mesh)."""
    mesh = st.get_mesh()
    ratios = {}
    for name, (expr, donate) in _matrix().items():
        plan = _plan_for(expr)
        assert plan is not None and plan.report is not None
        m = plan.report.get("memory")
        assert m is not None, f"{name}: no memory estimate on report"
        assert m["peak_bytes_per_chip"] > 0
        # donated positions: match the donated DistArray identity
        # against the plan's leaf order via the signing context
        donated_arrs = [d.value if isinstance(d, ValExpr) else d
                        for d in donate]
        plan_key, rctx = base.plan_signature(expr, mesh)
        dpos = tuple(
            i for i, leaf in enumerate(rctx.leaves)
            if any(base._leaf_array(leaf) is d for d in donated_arrs))
        assert not donate or dpos, f"{name}: donated leaf not found"
        v = mem.validate_plan(plan, mesh, donate_pos=dpos)
        assert v is not None, f"{name}: validation unavailable"
        ratios[name] = v["error_ratio"]
        assert 0.75 <= v["error_ratio"] <= 1.25, (
            f"{name}: predicted {v['predicted_bytes']} vs XLA "
            f"{v['xla_peak_bytes']} (ratio {v['error_ratio']}); "
            f"all so far: {ratios}")


def test_estimator_metrics_and_explain_surface():
    rng = np.random.RandomState(1)
    a = st.from_numpy(rng.rand(256, 256).astype(np.float32))
    e = st.dot(a, a) + 1.0
    plan = _plan_for(e)
    m = plan.report["memory"]
    assert m["args_bytes"] > 0 and m["out_bytes"] > 0
    assert m["top"], "top contributors missing"
    assert {"node", "bytes"} <= set(m["top"][0])
    gauges = st.metrics()["gauges"]
    assert gauges.get("memory_predicted_bytes", {}).get("value", 0) > 0
    mem.validate_plan(plan)
    assert "memory_prediction_error_ratio" in st.metrics()["gauges"]
    text = str(st.explain(e, cost=False))
    assert "memory: predicted peak" in text


def test_predict_helper_and_budget_autodetect_cpu():
    rng = np.random.RandomState(2)
    x = st.from_numpy(rng.rand(64, 64).astype(np.float32))
    m = mem.predict(x + x)
    assert m is not None and m["peak_bytes_per_chip"] > 0
    # CPU exposes no memory_stats: without an explicit flag there is
    # no budget and the governor stays inert
    FLAGS.hbm_budget_bytes = 0
    assert mem.hbm_budget_bytes() is None


# -- predictive degradation ----------------------------------------------


def _big_dot(seed=3, n=512):
    rng = np.random.RandomState(seed)
    a = st.from_numpy(rng.rand(n, n).astype(np.float32))
    b = st.from_numpy(rng.rand(n, n).astype(np.float32))
    return st.dot(a, b)


def test_predictive_rung_zero_reactive_retries():
    """The ISSUE-8 acceptance: under a tiny budget the rung is chosen
    BEFORE the first dispatch — zero reactive OOM events / retries in
    the resilience counters — and the result is bit-identical to the
    reactively-degraded path."""
    oracle_expr = _big_dot()
    oracle = oracle_expr.glom()

    # reactive reference: one injected OOM on the normal plan's first
    # dispatch, so the PR-5 ladder degrades AFTER a real failure
    base.clear_compile_cache()
    st.chaos("oom@0")
    reactive_expr = _big_dot()
    reactive_np = reactive_expr.glom()
    st.chaos_clear()
    assert reactive_expr._resilience["origin"] == "reactive"
    reactive_rung = reactive_expr._resilience["rung"]

    # predictive run under a budget the normal plan exceeds; 700k
    # admits finer_tiling (~655k/chip), the same rung the reactive
    # ladder reached — so the two paths are directly comparable
    base.clear_compile_cache()
    FLAGS.hbm_budget_bytes = 700_000
    before_oom = _counter("resilience_oom_events")
    before_retry = _counter("resilience_retries")
    before_pred = _counter("resilience_predictive_degrades")
    e = _big_dot()
    result = e.evaluate()
    out = result.glom()
    assert _counter("resilience_oom_events") == before_oom, \
        "predictive pick must not burn a doomed dispatch"
    assert _counter("resilience_retries") == before_retry
    assert _counter("resilience_predictive_degrades") == before_pred + 1
    rec = e._resilience
    assert rec["origin"] == "predictive"
    assert rec["rung"] in degrade.RUNGS
    assert rec["rung"] == reactive_rung
    np.testing.assert_array_equal(out, oracle)
    np.testing.assert_array_equal(out, reactive_np)


def test_predictive_pick_prefers_cheapest_sufficient_rung():
    # finer_tiling's re-plan fits a 700k budget for the 512x512 GEMM
    # (measured ~655k/chip on the 4x2 mesh); the dot must NOT fall all
    # the way to the chunked spill rung
    FLAGS.hbm_budget_bytes = 700_000
    e = _big_dot(seed=4)
    e.evaluate()
    assert e._resilience["rung"] == "finer_tiling"
    assert e._resilience["rung_predicted_bytes"] <= 700_000


def test_governed_plan_hit_redirects():
    FLAGS.hbm_budget_bytes = 600_000
    first = _big_dot(seed=5)
    oracle = first.glom()
    before = _counter("memory_governor_redirects")
    again = _big_dot(seed=5)
    out = again.glom()
    np.testing.assert_array_equal(out, oracle)
    assert _counter("memory_governor_redirects") == before + 1
    assert again._resilience["origin"] == "predictive"


def test_within_budget_runs_ungoverned():
    FLAGS.hbm_budget_bytes = 1 << 30
    before = (_counter("memory_governor_redirects"),
              _counter("resilience_predictive_degrades"))
    e = _big_dot(seed=6)
    e.evaluate()
    assert getattr(e, "_resilience", None) is None
    assert (_counter("memory_governor_redirects"),
            _counter("resilience_predictive_degrades")) == before


def test_governor_off_leaves_reactive_path():
    """``oom@`` chaos still exercises the REACTIVE fallback: with no
    budget (CPU auto-detect = None) an injected dispatch OOM walks the
    PR-5 ladder exactly as before the governor existed."""
    FLAGS.hbm_budget_bytes = 0
    before_oom = _counter("resilience_oom_events")
    st.chaos("oom@0")
    e = _big_dot(seed=7)
    oracle = np.asarray(e.glom())
    rec = e._resilience
    assert rec["origin"] == "reactive"
    assert rec["rung"] in degrade.RUNGS
    assert _counter("resilience_oom_events") == before_oom + 1
    # the reactive record carries the rung's own predicted peak so bug
    # reports can tell model-missed from model-absent
    if rec["rung"] != "chunked":
        assert rec.get("rung_predicted_bytes", 0) > 0
    st.chaos_clear()
    clean = _big_dot(seed=7)
    np.testing.assert_array_equal(oracle, clean.glom())


def test_predictive_wrong_model_falls_back_reactive():
    """When the chosen rung STILL OOMs (the model was wrong), the
    reactive ladder takes over instead of failing the evaluation."""
    FLAGS.hbm_budget_bytes = 700_000  # predictive picks finer_tiling
    before_oom = _counter("resilience_oom_events")
    st.chaos("oom@0")  # ...whose first dispatch is injected to OOM
    e = _big_dot(seed=8)
    out = e.glom()
    st.chaos_clear()
    assert _counter("resilience_oom_events") == before_oom + 1
    np.testing.assert_array_equal(out, _big_dot(seed=8).glom())


# -- serve: memory-aware admission ---------------------------------------


def test_serve_reservation_ledger_returns_to_zero():
    from spartan_tpu.serve.engine import ServeEngine

    FLAGS.hbm_budget_bytes = 1 << 30  # roomy: admit the whole burst
    rng = np.random.RandomState(9)
    x = st.from_numpy(rng.rand(256, 64).astype(np.float32))
    with ServeEngine(workers=2, batch_window_s=0.0) as eng:
        futures = [eng.submit((x * float(i)).sum()) for i in range(12)]
        for i, f in enumerate(futures):
            got = float(f.glom(timeout=30))
            want = float((np.asarray(x.glom()) * float(i)).sum())
            np.testing.assert_allclose(got, want, rtol=1e-4)
        assert eng.ledger.reserved() == 0
    snap = st.metrics()["gauges"].get("serve_mem_reserved_bytes")
    assert snap is not None and snap["value"] == 0.0
    assert snap["max"] > 0.0, "burst never reserved anything"


def test_serve_admission_backpressure_on_budget_overflow():
    from spartan_tpu.serve.engine import ServeEngine, _Request

    rng = np.random.RandomState(10)
    x = st.from_numpy(rng.rand(512, 256).astype(np.float32))
    e = (x * 2.0).sum()
    # pre-build the plan so request_bytes uses the modeled peak
    plan = _plan_for(e)
    peak = plan.report["memory"]["peak_bytes_per_chip"]
    FLAGS.hbm_budget_bytes = int(peak * 1.5)
    eng = ServeEngine(workers=1)
    # saturate the ledger by hand (as if a dispatch were in flight)
    eng.ledger.reserve(int(peak))
    with pytest.raises(st.Backpressure):
        eng.submit((x * 2.0).sum())
    assert _counter("serve_mem_rejected") >= 1
    eng.ledger.release(int(peak))
    fut = eng.submit((x * 2.0).sum())
    want = float((np.asarray(x.glom()) * 2.0).sum())
    np.testing.assert_allclose(float(fut.glom(timeout=30)), want,
                               rtol=1e-4)
    eng.stop()


# -- tiling DP soft memory term ------------------------------------------


def test_tiling_memory_weight_prefers_finer_and_rekeys():
    mesh = st.get_mesh()
    rng = np.random.RandomState(11)
    a = st.from_numpy(rng.rand(512, 512).astype(np.float32))
    b = st.from_numpy(rng.rand(512, 512).astype(np.float32))
    oracle = np.asarray(a.glom()) @ np.asarray(b.glom())

    def build(weight):
        FLAGS.tiling_memory_weight = weight
        e = st.dot(a, b)
        plan_key, _rctx = base.plan_signature(e, mesh)
        return e, plan_key, _plan_for(e)

    e0, pk0, plan0 = build(0.0)
    e1, pk1, plan1 = build(50.0)
    # the weight is part of the plan-cache key: no stale aliasing
    assert pk0 != pk1
    # a strong memory term pushes the DP to a finer (lower-residency)
    # plan than the pure-speed optimum
    peak0 = plan0.report["memory"]["peak_bytes_per_chip"]
    peak1 = plan1.report["memory"]["peak_bytes_per_chip"]
    assert peak1 < peak0, (peak0, peak1)
    # numerics unchanged under the re-plan
    np.testing.assert_allclose(np.asarray(e1.glom()), oracle,
                               rtol=1e-4)
    FLAGS.tiling_memory_weight = 0.0


# -- multi-device memory read-outs (satellite 1) -------------------------


def test_device_memory_aggregate_shape():
    from spartan_tpu.obs.metrics import device_memory_aggregate

    agg = device_memory_aggregate()
    assert isinstance(agg, dict)
    for key, v in agg.items():
        assert set(v) == {"max", "sum"}
        assert v["sum"] >= v["max"]


def test_status_memory_stats_aggregated():
    s = st.status()
    assert isinstance(s["memory_stats"], dict)
    for key, v in s["memory_stats"].items():
        assert set(v) == {"max", "sum"}
