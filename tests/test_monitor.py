"""Closed-loop telemetry (ISSUE 18): SLO classes + burn windows, the
continuous monitor's detector matrix, epoch fencing, and the autotune
daemon's refit -> replan -> hysteresis-gated hot-swap chain — plan-key
separation and bit-stable numerics included."""

import json

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.array import distarray as da
from spartan_tpu.array import tiling as tiling_mod
from spartan_tpu.expr import base
from spartan_tpu.obs import ledger
from spartan_tpu.obs import monitor
from spartan_tpu.obs import slo
from spartan_tpu.obs import trace as trace_mod
from spartan_tpu.obs.explain import key_hash
from spartan_tpu.obs.metrics import REGISTRY, labeled
from spartan_tpu.parallel import mesh as mesh_mod
from spartan_tpu.serve import engine as engine_mod
from spartan_tpu.serve.future import Backpressure, DeadlineExceeded
from spartan_tpu.utils.config import FLAGS

_SAVED = (
    "serve_slo_classes", "serve_slo_tenants", "serve_slo_window",
    "monitor", "monitor_interval_s", "monitor_window",
    "monitor_autotune", "monitor_drift_patience",
    "monitor_swap_margin", "monitor_cooldown_s",
    "monitor_burn_threshold", "monitor_fallback_rate",
    "monitor_fleet_dir", "cost_ledger", "cost_calibration",
    "cost_calibration_fingerprint", "calibration_drift_tol",
    "serve_model_pricing",
)


@pytest.fixture(autouse=True)
def _setup(mesh1d):
    saved = {n: getattr(FLAGS, n) for n in _SAVED}
    FLAGS.cost_ledger = True
    monitor.MONITOR.stop()
    monitor.MONITOR.reset()
    ledger.set_profile(None)
    ledger.reset()
    slo.reset()
    st.serve.shutdown_default()
    trace_mod.clear()
    yield
    monitor.MONITOR.stop()
    monitor.MONITOR.reset()
    st.serve.shutdown_default()
    ledger.set_profile(None)
    ledger.reset()
    slo.reset()
    for n, v in saved.items():
        setattr(FLAGS, n, v)


def _trace_names():
    return [s.name for s in trace_mod.events()]


# -- SLO classes + burn windows ------------------------------------------


def test_slo_class_parsing_matrix():
    FLAGS.serve_slo_classes = (
        "gold=0.05@0.999:0.25, bulk=2.0, nonsense")
    FLAGS.serve_slo_tenants = "t1=gold, svc=bulk"
    table = slo.classes()
    assert set(table) == {"gold", "bulk"}
    g = table["gold"]
    assert (g.target_s, g.objective, g.share) == (0.05, 0.999, 0.25)
    b = table["bulk"]
    assert (b.target_s, b.objective, b.share) == (2.0, 0.99, 1.0)
    assert abs(g.budget() - 0.001) < 1e-12

    assert slo.class_for("t1").name == "gold"
    assert slo.class_for("svc").name == "bulk"
    # unmapped tenant with no 'default' class: untracked
    assert slo.class_for("stranger") is None
    assert slo.class_for(None) is None

    # a declared 'default' class catches every unmapped tenant
    FLAGS.serve_slo_classes = "default=1.0@0.9"
    assert slo.class_for("stranger").name == "default"
    # objective is clamped below 1.0 (the budget can never be zero)
    FLAGS.serve_slo_classes = "x=1.0@1.0"
    assert slo.classes()["x"].objective <= 0.999999
    FLAGS.serve_slo_classes = ""
    assert slo.classes() == {}
    assert slo.class_for("t1") is None


def test_slo_burn_tracking_and_prometheus_export():
    FLAGS.serve_slo_classes = "gold=0.001@0.9"
    FLAGS.serve_slo_tenants = "hot=gold"
    FLAGS.serve_slo_window = 16
    for _ in range(10):
        slo.observe("hot", 0.05)  # every one a violation
    slo.observe("untracked-tenant", 0.05)  # no-op

    burns = slo.burn_rates()
    rec = burns["gold"]
    assert rec["window"] == 10
    assert rec["violation_rate"] == pytest.approx(1.0)
    # burn = violation rate over the 10% error budget
    assert rec["burn_rate"] == pytest.approx(10.0)
    assert rec["target_s"] == 0.001 and rec["queue_share"] == 1.0

    assert REGISTRY.counter(
        labeled("slo_requests_total", slo_class="gold")).value == 10
    assert REGISTRY.counter(
        labeled("slo_violations_total", slo_class="gold")).value == 10

    text = st.metrics(fmt="prometheus")
    assert "# HELP spartan_slo_burn_rate " in text
    assert "# TYPE spartan_slo_burn_rate gauge" in text
    assert 'spartan_slo_burn_rate{slo_class="gold"}' in text


def test_slo_window_is_bounded():
    FLAGS.serve_slo_classes = "gold=10.0@0.9"
    FLAGS.serve_slo_tenants = "hot=gold"
    FLAGS.serve_slo_window = 8
    for _ in range(8):
        slo.observe("hot", 100.0)  # violations fill the window
    for _ in range(8):
        slo.observe("hot", 0.0)  # then healthy samples evict them
    rec = slo.burn_rates()["gold"]
    assert rec["window"] == 8
    assert rec["violation_rate"] == pytest.approx(0.0)
    assert rec["burn_rate"] == pytest.approx(0.0)


# -- the detector matrix --------------------------------------------------


def test_sustained_detector_patience_and_no_reemit():
    FLAGS.monitor_drift_patience = 3
    d = monitor._SustainedDetector("test_kind")
    breach = {"k": (5.0, 1.0, True, "hot")}
    calm = {"k": (0.5, 1.0, False, "ok")}
    assert d.feed(0.0, breach) == []
    assert d.feed(1.0, breach) == []
    out = d.feed(2.0, breach)
    assert len(out) == 1 and out[0].kind == "test_kind"
    assert out[0].key == "k" and out[0].value == 5.0
    # still breached: the streak keeps counting, no re-emit
    assert d.feed(3.0, breach) == []
    assert d.feed(4.0, breach) == []
    # recovery resets; a fresh sustained breach emits ONE more
    assert d.feed(5.0, calm) == []
    assert d.streak("k") == 0
    assert d.feed(6.0, breach) == []
    assert d.feed(7.0, breach) == []
    assert len(d.feed(8.0, breach)) == 1


def test_sustained_detector_oscillation_never_emits():
    FLAGS.monitor_drift_patience = 2
    d = monitor._SustainedDetector("test_kind")
    for i in range(10):
        obs = {"k": (1.0, 1.0, i % 2 == 0, "flap")}
        assert d.feed(float(i), obs) == []


def test_fallback_detector_primes_then_spikes():
    FLAGS.monitor_drift_patience = 1
    FLAGS.monitor_fallback_rate = 2.0
    d = monitor._FallbackDetector()
    assert d.observe(0.0, {"serve_solo_fallbacks": 100}) == []  # prime
    out = d.observe(1.0, {"serve_solo_fallbacks": 105})
    assert len(out) == 1
    assert out[0].kind == "fallback_spike"
    assert out[0].key == "serve_solo_fallbacks"
    assert out[0].value == 5.0
    # steady counter: delta 0, below the rate — no anomaly
    assert d.observe(2.0, {"serve_solo_fallbacks": 105}) == []
    # a slow drip under the threshold never fires
    assert d.observe(3.0, {"serve_solo_fallbacks": 106}) == []


def test_backpressure_detector_needs_rejections_and_depth():
    FLAGS.monitor_drift_patience = 1
    d = monitor._BackpressureDetector()
    assert d.observe(0.0, 0, 0) == []  # prime
    out = d.observe(1.0, 3, 2)  # rejections grew, queue non-empty
    assert len(out) == 1 and out[0].kind == "backpressure"
    # rejections grew but the queue drained: a burst, not saturation
    assert d.observe(2.0, 0, 5) == []


def test_monitor_sample_emits_drift_anomaly():
    FLAGS.calibration_drift_tol = 0.3
    FLAGS.monitor_drift_patience = 2
    for _ in range(6):  # predicted 5x the measured service time
        ledger.note_service("drifting-plan", 0.5, 0.1)
    assert monitor.sample() == []  # streak 1 of 2
    out = monitor.sample()
    assert len(out) == 1
    a = out[0]
    assert a.kind == "calibration_drift" and a.key == "service_time"
    assert a.value == pytest.approx(5.0, rel=0.01)
    assert list(monitor.MONITOR.anomalies)[-1] is a
    # the series store sampled the ratio, the counter and trace fired
    series = monitor.MONITOR.store.series(
        "calibration_error_ratio:service_time")
    assert series is not None and len(series.values()) == 2
    assert REGISTRY.counter(labeled(
        "monitor_anomalies_total",
        kind="calibration_drift")).value >= 1
    assert "anomaly" in _trace_names()
    d = a.to_dict()
    assert d["kind"] == "calibration_drift" and d["value"] == a.value


def test_monitor_sample_emits_burn_anomaly():
    FLAGS.serve_slo_classes = "gold=0.001@0.9"
    FLAGS.serve_slo_tenants = "hot=gold"
    FLAGS.monitor_burn_threshold = 1.0
    FLAGS.monitor_drift_patience = 1
    for _ in range(10):
        slo.observe("hot", 1.0)
    out = monitor.sample()
    assert [a.kind for a in out] == ["slo_burn"]
    assert out[0].key == "gold"
    series = monitor.MONITOR.store.series("slo_burn_rate:gold")
    assert series is not None
    assert series.latest() == pytest.approx(10.0)


# -- epoch fencing --------------------------------------------------------


def test_epoch_fence_resets_streaks_and_templates():
    FLAGS.calibration_drift_tol = 0.3
    FLAGS.monitor_drift_patience = 5
    for _ in range(4):
        ledger.note_service("drifting-plan", 0.5, 0.1)
    monitor.sample()
    monitor.sample()
    assert monitor.MONITOR.drift.streak("service_time") == 2
    monitor.MONITOR.autotune.register("dead-digest", object())

    before = REGISTRY.counter("monitor_epoch_fences").value
    monitor.MONITOR._epoch_seen = mesh_mod.mesh_epoch() - 1
    assert monitor.sample() == []  # fenced tick: quiet by design
    assert monitor.MONITOR._epoch_seen == mesh_mod.mesh_epoch()
    assert monitor.MONITOR.drift.streak("service_time") == 0
    assert monitor.MONITOR.autotune.templates() == {}
    assert REGISTRY.counter("monitor_epoch_fences").value == before + 1
    assert "monitor_epoch_fence" in _trace_names()


def test_notify_mesh_recovery_fences_immediately():
    monitor.sample()  # prime the epoch
    monitor.MONITOR.autotune.register("dead-digest", object())
    monitor.notify_mesh_recovery()
    assert monitor.MONITOR.autotune.templates() == {}
    assert monitor.MONITOR._epoch_seen == mesh_mod.mesh_epoch()


# -- the autotune daemon --------------------------------------------------


def _synthetic_rows(true_factors, rows=12, seed=7, scale=1e-6):
    rng = np.random.RandomState(seed)
    classes = sorted(true_factors)
    for i in range(rows):
        comp = {c: float(rng.uniform(10.0, 100.0)) for c in classes}
        measured = scale * sum(true_factors[c] * comp[c]
                               for c in classes)
        ledger.ingest(f"syn-{i}", comp, measured)


def _events(kind=None):
    evs = list(monitor.MONITOR.autotune.events)
    return [e for e in evs if kind is None or e["event"] == kind]


def test_autotune_skip_reasons_and_hysteresis():
    FLAGS.monitor_cooldown_s = 50.0
    auto = monitor.MONITOR.autotune

    # empty ledger: nothing fittable, but the cooldown still starts
    assert auto.attempt(0.0) is None
    assert _events("skip")[-1]["reason"] == "nothing_fittable"
    assert auto.state == "cooldown" and auto.in_cooldown(10.0)

    # fittable skew but NO hot-plan templates: nothing replannable,
    # the trial reverts and the incumbent (no profile) is restored
    _synthetic_rows({"map": 1.0, "reshard": 4.0})
    assert auto.attempt(100.0) == "revert"
    rev = _events("revert")[-1]
    assert rev["replanned"] == 0
    assert ledger.active_profile() is None
    assert FLAGS.cost_calibration is False
    assert auto.last_rejected_fp == rev["fingerprint"]

    # the rejected fingerprint is remembered: no flapping
    assert auto.attempt(200.0) is None
    assert _events("skip")[-1]["reason"] == "recently_rejected"

    # tick() honors the cooldown: a fresh drift anomaly inside it
    # only parks the state machine
    n_events = len(_events())
    anom = monitor.Anomaly("calibration_drift", "tiling_dp", 210.0,
                           5.0, 0.3, "test")
    auto.tick(210.0, [anom])
    assert auto.state == "cooldown"
    assert len(_events()) == n_events
    # and with no anomalies outside the cooldown it goes idle
    auto.tick(1000.0, [])
    assert auto.state == "idle"


def _gemm(n, seed=11):
    """Row-tiled n x n gemm. Plan keys are STRUCTURAL (shape+tiling,
    not values), so each test that needs its own plan-build miss —
    the autotune template hook fires only there — uses a distinct n."""
    rng = np.random.RandomState(seed)
    a = da.from_numpy(rng.rand(n, n).astype(np.float32),
                      tiling=tiling_mod.row(2))
    b = da.from_numpy(rng.rand(n, n).astype(np.float32),
                      tiling=tiling_mod.row(2))
    return lambda: st.dot(st.as_expr(a), st.as_expr(b))


def test_autotune_hot_swap_acceptance():
    """The chaos-seeded mispriced-psum scenario: measurements say
    output all-reduces cost ~10x the model's price. The daemon must
    refit, replan the registered hot template under the candidate,
    clear the hysteresis margin, hot-swap — and the re-keyed plan must
    produce the same numbers."""
    FLAGS.monitor_autotune = True
    FLAGS.monitor_swap_margin = 0.05
    build = _gemm(96)
    key0 = base.plan_signature(build())[0]
    v0 = np.asarray(build().glom())
    # the plan-build miss registered a result-free template
    assert key_hash(key0) in monitor.MONITOR.autotune.templates()

    _synthetic_rows({"map": 1.0, "contraction": 1.0, "reshard": 1.0,
                     "psum": 10.0})
    assert monitor.MONITOR.autotune.attempt(0.0) == "swap"
    ev = _events("swap")[-1]
    assert ev["modeled_win"] >= 0.05
    assert ev["replanned"] >= 1 and ev["warmed"] >= 1
    assert _events("refit")  # refit precedes the swap in the log

    # the candidate stayed installed: plans re-key (separation), the
    # calibrated DP picks a different strategy, numerics are stable
    assert FLAGS.cost_calibration is True
    assert ledger.active_profile() is not None
    key1 = base.plan_signature(build())[0]
    assert key1 != key0
    v1 = np.asarray(build().glom())
    np.testing.assert_allclose(v0, v1, rtol=1e-5)
    assert "autotune_swap" in _trace_names()

    # rolling the flag back re-keys onto the untouched incumbent
    FLAGS.cost_calibration = False
    assert base.plan_signature(build())[0] == key0


def test_autotune_closed_loop_via_sample():
    """Drift anomaly -> tick -> refit -> swap, driven end to end
    through Monitor.sample() — the chain an operator reads back from
    st.status()."""
    FLAGS.monitor_autotune = True
    FLAGS.monitor_drift_patience = 1
    FLAGS.monitor_cooldown_s = 0.0
    FLAGS.calibration_drift_tol = 0.3
    FLAGS.monitor_swap_margin = 0.05
    build = _gemm(112, seed=12)
    v0 = np.asarray(build().glom())
    _synthetic_rows({"map": 1.0, "contraction": 1.0, "reshard": 1.0,
                     "psum": 10.0}, seed=8)
    for _ in range(4):  # sustained service-time mispricing
        ledger.note_service("drifting-plan", 0.5, 0.1)

    out = monitor.sample()
    assert any(a.kind == "calibration_drift" for a in out)
    kinds = [e["event"] for e in _events()]
    assert "refit" in kinds and "swap" in kinds

    status = st.status()
    assert status["daemon"]["state"] == "cooldown"
    assert [e for e in status["daemon"]["events"]
            if e["event"] == "swap"]
    assert any(a["kind"] == "calibration_drift"
               for a in status["anomalies"])
    np.testing.assert_allclose(v0, np.asarray(build().glom()),
                               rtol=1e-5)


def test_autotune_no_swap_when_model_already_calibrated():
    """A UNIFORM measured workload (the model is right) must never
    flap the plans: the fitted factors reprice nothing, the modeled
    win stays under the margin, the daemon reverts."""
    FLAGS.monitor_autotune = True
    FLAGS.monitor_swap_margin = 0.05
    build = _gemm(80, seed=13)
    key0 = base.plan_signature(build())[0]
    build().glom()
    assert monitor.MONITOR.autotune.templates()

    _synthetic_rows({"map": 1.0, "contraction": 1.0, "reshard": 1.0,
                     "psum": 1.0}, seed=9)
    assert monitor.MONITOR.autotune.attempt(0.0) == "revert"
    assert ledger.active_profile() is None
    assert FLAGS.cost_calibration is False
    assert base.plan_signature(build())[0] == key0


# -- surfaces -------------------------------------------------------------


def test_status_has_monitoring_sections_on_top_of_mesh_contract():
    FLAGS.serve_slo_classes = "gold=0.5@0.99"
    st.serve.default_engine()
    s = st.status()
    # the long-standing mesh keys stay top-level
    for k in ("platform", "num_devices", "mesh", "process_index",
              "memory_stats"):
        assert k in s
    assert s["serve"] is not None and "queue_depth" in s["serve"]
    assert "gold" in s["slo"]
    assert s["daemon"]["state"] == "idle"
    assert s["calibration"]["enabled"] is False
    assert s["monitor"]["running"] is False
    assert isinstance(s["anomalies"], list)


def test_fleet_status_aggregates_ranks_and_skips_corrupt(tmp_path):
    FLAGS.monitor_fleet_dir = str(tmp_path / "fleet")
    FLAGS.serve_slo_classes = "gold=0.001@0.9"
    FLAGS.serve_slo_tenants = "hot=gold"
    for _ in range(10):
        slo.observe("hot", 1.0)

    fs = st.fleet_status()
    assert fs["fleet_dir"] == FLAGS.monitor_fleet_dir
    assert fs["ranks_reporting"] == 1 and 0 in fs["ranks"]
    assert fs["slo_worst"]["gold"]["rank"] == 0

    # a peer rank reports a hotter burn; a torn file is skipped
    peer = {"rank": 1, "wall_t": 0.0,
            "status": {"slo": {"gold": {"burn_rate": 99.0}},
                       "anomalies": [{"kind": "slo_burn"}] * 3}}
    (tmp_path / "fleet" / "rank_1.json").write_text(json.dumps(peer))
    (tmp_path / "fleet" / "rank_2.json").write_text("{torn")
    fs = st.fleet_status()
    assert fs["ranks_reporting"] == 2
    assert fs["slo_worst"]["gold"] == {"burn_rate": 99.0, "rank": 1}
    assert fs["anomalies_total"] >= 3

    # without a fleet dir it degrades to the single-rank view
    FLAGS.monitor_fleet_dir = ""
    fs = st.fleet_status()
    assert fs["fleet_dir"] is None and 0 in fs["ranks"]


def test_monitor_thread_lifecycle_and_crash_section():
    FLAGS.monitor = True
    FLAGS.monitor_interval_s = 0.02
    monitor.start()
    try:
        deadline = 100
        while (monitor.MONITOR.health()["samples"] == 0
               and deadline > 0):
            import time

            time.sleep(0.02)
            deadline -= 1
        h = monitor.MONITOR.health()
        assert h["running"] is True and h["samples"] >= 1
    finally:
        monitor.stop()
    assert monitor.MONITOR.health()["running"] is False

    sec = monitor.crash_section()
    assert set(sec) == {"health", "anomalies", "daemon",
                        "series_tail"}
    assert sec["daemon"]["state"] in ("idle", "cooldown")


def test_registry_snapshot_reset_is_atomic_and_keeps_keys():
    REGISTRY.counter("tmon_ctr", "test counter").inc(5)
    REGISTRY.gauge("tmon_gauge", "test gauge").set(2.5)
    REGISTRY.histogram("tmon_hist", "test histogram").observe(1.25)
    snap = REGISTRY.snapshot(reset=True)
    assert snap["counters"]["tmon_ctr"] == 5
    assert snap["gauges"]["tmon_gauge"]["value"] == 2.5
    assert snap["histograms"]["tmon_hist"]["count"] == 1
    # the read-and-zero was one critical section: the keys survive,
    # the values start over
    snap2 = REGISTRY.snapshot()
    assert snap2["counters"]["tmon_ctr"] == 0
    assert snap2["gauges"]["tmon_gauge"]["value"] == 0.0
    assert snap2["histograms"]["tmon_hist"]["count"] == 0
    # st.metrics(reset=...) rides the same path
    m = st.metrics(reset=True)
    assert "counters" in m


# -- serve integration: SLO admission + model-priced shedding -------------


def _fresh_expr(seed=21):
    rng = np.random.RandomState(seed)
    return (st.as_expr(rng.rand(16, 16).astype(np.float32))
            + st.as_expr(rng.rand(16, 16).astype(np.float32)))


def test_slo_class_queue_share_admission():
    FLAGS.serve_slo_classes = "bulk=5.0@0.9:0.5"
    FLAGS.serve_slo_tenants = "b=bulk"
    engine = st.ServeEngine(workers=1, queue_max=4,
                            batch_window_s=0.0)
    # park the engine: submit() auto-starts workers (which would
    # drain the queue and make depth non-deterministic), so satisfy
    # its running check with one already-finished placeholder thread.
    # Submissions then sit in the queue; bulk's share of the 4-deep
    # queue is 2 slots.
    import threading

    placeholder = threading.Thread(target=lambda: None)
    placeholder.start()
    placeholder.join()
    engine._threads.append(placeholder)
    try:
        engine.submit(_fresh_expr(30), tenant="b")
        engine.submit(_fresh_expr(31), tenant="b")
        with pytest.raises(Backpressure):
            engine.submit(_fresh_expr(32), tenant="b")
        assert REGISTRY.counter(labeled(
            "serve_slo_rejected", slo_class="bulk")).value == 1
        # an untracked tenant still has the full queue available
        engine.submit(_fresh_expr(33), tenant="other")
    finally:
        engine.stop()


def test_model_priced_predictive_shed():
    """A request whose calibrated price exceeds its remaining deadline
    is shed at pop time WITHOUT burning the dispatch slot — and the
    rejection names the prediction."""
    assert FLAGS.serve_model_pricing is True
    # warm the seconds-per-cost-unit EMA at exactly 1 s/unit
    ledger.ingest("ema-warm", {"map": 1.0}, 1.0)
    for _ in range(8):
        ledger.note_dispatch("ema-warm", "dispatch", 1.0)
    assert ledger.predict_service_s("ema-warm") == pytest.approx(
        1.0, rel=1e-6)

    engine = st.ServeEngine(workers=1, queue_max=4)
    try:
        doomed = engine_mod._Request(
            _fresh_expr(40), [], "t", 5.0, mesh_mod.get_mesh())
        ledger.ingest(key_hash(doomed.plan_key),
                      {"map": 100.0}, 100.0)  # priced at ~100 s
        before = REGISTRY.counter("serve_predicted_shed").value
        live = engine._shed_expired([doomed])
        assert live == []
        assert REGISTRY.counter(
            "serve_predicted_shed").value == before + 1
        with pytest.raises(DeadlineExceeded, match="predicted"):
            doomed.future.result(timeout=1)

        # an affordable request under the same deadline sails through
        ok = engine_mod._Request(
            _fresh_expr(41), [], "t", 5.0, mesh_mod.get_mesh())
        ledger.ingest(key_hash(ok.plan_key), {"map": 0.001}, 0.001)
        assert engine._shed_expired([ok]) == [ok]
    finally:
        engine.stop()


def test_predictive_shed_requires_model_pricing():
    FLAGS.serve_model_pricing = False
    ledger.ingest("ema-warm", {"map": 1.0}, 1.0)
    for _ in range(8):
        ledger.note_dispatch("ema-warm", "dispatch", 1.0)
    engine = st.ServeEngine(workers=1, queue_max=4)
    try:
        req = engine_mod._Request(
            _fresh_expr(42), [], "t", 5.0, mesh_mod.get_mesh())
        ledger.ingest(key_hash(req.plan_key), {"map": 100.0}, 100.0)
        # EMA-era behavior: only already-expired deadlines shed
        assert engine._shed_expired([req]) == [req]
    finally:
        engine.stop()
