"""Device-time cost ledger + profile-guided calibration (ISSUE 9):
predictions recorded next to measurements for all three models
(tiling-DP cost, peak HBM, service time), drift counting, per-op-class
factor fitting, and the calibration flag flipping a tiling-DP choice
under plan-key separation."""

import math

import numpy as np
import pytest

import spartan_tpu as st
from spartan_tpu.array import distarray as da
from spartan_tpu.array import tiling as tiling_mod
from spartan_tpu.expr import base, tiling_cost
from spartan_tpu.obs import ledger
from spartan_tpu.obs.explain import key_hash
from spartan_tpu.obs.metrics import REGISTRY, labeled
from spartan_tpu.utils.config import FLAGS


@pytest.fixture(autouse=True)
def _setup(mesh1d):
    saved = {n: getattr(FLAGS, n) for n in (
        "cost_ledger", "cost_calibration",
        "cost_calibration_fingerprint", "calibration_drift_tol")}
    FLAGS.cost_ledger = True
    ledger.set_profile(None)
    ledger.reset()
    st.serve.shutdown_default()
    yield
    st.serve.shutdown_default()
    ledger.set_profile(None)
    ledger.reset()
    for n, v in saved.items():
        setattr(FLAGS, n, v)


def _leaves(seed=0):
    rng = np.random.RandomState(seed)
    x = st.as_expr(rng.rand(256, 64).astype(np.float32)).evaluate()
    y = st.as_expr(rng.rand(256, 64).astype(np.float32)).evaluate()
    a = st.as_expr(rng.rand(128, 128).astype(np.float32)).evaluate()
    w = st.as_expr(rng.rand(128, 128).astype(np.float32)).evaluate()
    return x, y, a, w


def _matrix(name, x, y, a, w):
    """Fresh structurally-identical exprs per call (results cache on
    nodes, so reuse would skip the dispatch being measured)."""
    xe, ye, ae, we = (st.as_expr(v) for v in (x, y, a, w))
    if name == "map":
        return (xe + ye) * 3.0 - xe
    if name == "dot":
        return st.dot(ae, ae)
    if name == "reduce":
        return (xe * xe).sum(axis=0)
    return st.loop(4, lambda c: c * 0.5 + ae, we)


NAMES = ("map", "dot", "reduce", "loop")


# -- the loop-closing acceptance test ------------------------------------


def test_ledger_closes_loop_on_cpu_matrix():
    """For the {map, dot, reduce, loop} plans, st.ledger() reports
    measured-vs-predicted ratios for ALL THREE models: the tiling DP
    (scale-normalized dispatch time), peak HBM (XLA memory_analysis
    actuals), and service time (queue EMA vs measured service)."""
    leaves = _leaves()
    digests = {}
    for name in NAMES:
        for _ in range(3):  # compile once, then measured warm hits
            _matrix(name, *leaves).evaluate()
        digests[name] = key_hash(
            base.plan_signature(_matrix(name, *leaves))[0])

    with st.ServeEngine(workers=1, batch_window_s=0.0) as eng:
        for name in NAMES:
            eng.submit(_matrix(name, *leaves),
                       tenant="cal").result(timeout=120)

    snap = st.ledger(validate=True)
    for name, dig in digests.items():
        plan = snap["plans"].get(dig)
        assert plan is not None, (name, dig, sorted(snap["plans"]))
        for model in ("tiling_dp", "peak_hbm", "service_time"):
            r = plan["ratios"].get(model)
            assert r is not None and r > 0 and math.isfinite(r), \
                (name, model, plan)
        # predictions and measurements sit side by side
        assert plan["predicted"]["dp_cost"] > 0
        assert plan["predicted"]["cost_components"]
        assert plan["measured"]["dispatch_count"] >= 2
        assert plan["measured"]["xla_peak_bytes"] > 0
    models = snap["models"]
    for model in ("tiling_dp", "peak_hbm", "service_time"):
        assert models[model]["samples"] >= 4
        assert models[model]["calibration_error_ratio"] > 0
    assert models["tiling_dp"]["seconds_per_cost_unit"] > 0


def test_prometheus_gauges_per_model():
    leaves = _leaves(seed=1)
    for _ in range(3):
        _matrix("map", *leaves).evaluate()
    st.ledger(validate=True)
    text = st.metrics(fmt="prometheus")
    assert 'spartan_calibration_error_ratio{model="tiling_dp"}' in text
    assert 'spartan_calibration_error_ratio{model="peak_hbm"}' in text


def test_compile_and_dispatch_recorded_separately():
    # structurally identical plans from other tests would hit the
    # process-wide caches and skip the compile being asserted on
    base.clear_compile_cache()
    leaves = _leaves(seed=2)
    for _ in range(3):
        _matrix("reduce", *leaves).evaluate()
    dig = key_hash(base.plan_signature(_matrix("reduce", *leaves))[0])
    plan = st.ledger()["plans"][dig]
    meas = plan["measured"]
    assert meas["compile_s"] and meas["compile_s"] > 0
    assert meas["dispatch_count"] == 2  # first run was the compile
    assert meas["dispatch_min_s"] > 0
    assert meas["compile_s"] > meas["dispatch_min_s"]


def test_drift_counter_fires_past_tolerance():
    FLAGS.calibration_drift_tol = 0.1
    before = REGISTRY.counter(
        labeled("calibration_drift_total", model="service_time")).value
    # prediction 5x off the measurement: |log 5| > 0.1
    ledger.note_service("plan-x", predicted_s=0.5, measured_s=0.1)
    after = REGISTRY.counter(
        labeled("calibration_drift_total", model="service_time")).value
    assert after == before + 1
    # within tolerance: no drift
    ledger.note_service("plan-x", predicted_s=0.1, measured_s=0.1)
    assert REGISTRY.counter(labeled(
        "calibration_drift_total",
        model="service_time")).value == after


def test_ledger_off_records_nothing():
    FLAGS.cost_ledger = False
    leaves = _leaves(seed=3)
    for _ in range(2):
        _matrix("map", *leaves).evaluate()
    assert st.ledger()["plans"] == {}


# -- profile fitting + persistence ---------------------------------------


def _synthetic_rows(true_factors, rows=12, seed=7, scale=1e-6):
    """Ledger entries whose measured times follow a SKEWED cost model:
    measured = sum_c true_factors[c] * components[c] * scale."""
    rng = np.random.RandomState(seed)
    classes = sorted(true_factors)
    for i in range(rows):
        comp = {c: float(rng.uniform(10.0, 100.0)) for c in classes}
        measured = scale * sum(true_factors[c] * comp[c]
                               for c in classes)
        ledger.ingest(f"syn-{i}", comp, measured)


def test_fit_profile_recovers_relative_skew():
    true = {"map": 1.0, "contraction": 1.0, "reshard": 4.0, "psum": 1.0}
    _synthetic_rows(true)
    prof = ledger.fit_profile()
    assert prof is not None
    # factors are relative (cost-weighted mean ~1): the SKEW between
    # classes is what must be recovered
    ratio = prof.factors["reshard"] / prof.factors["map"]
    assert 3.2 < ratio < 4.8, prof.factors
    ratio2 = prof.factors["psum"] / prof.factors["map"]
    assert 0.8 < ratio2 < 1.25, prof.factors


def test_profile_save_load_roundtrip(tmp_path):
    prof = ledger.CalibrationProfile({"reshard": 2.5, "psum": 0.5},
                                     meta={"platform": "cpu"})
    path = str(tmp_path / "profile.json")
    st.save_profile(path, prof)
    loaded = st.load_profile(path)
    assert loaded.factors == prof.factors
    assert loaded.fingerprint() == prof.fingerprint()
    # load_profile installs: the fingerprint flag now keys plan keys
    assert FLAGS.cost_calibration_fingerprint == prof.fingerprint()
    assert ledger.active_profile() is loaded


def test_save_profile_fits_from_ledger_when_none_active(tmp_path):
    _synthetic_rows({"map": 1.0, "reshard": 3.0})
    path = st.save_profile(str(tmp_path / "fitted.json"))
    loaded = st.load_profile(path)
    assert loaded.factors["reshard"] / loaded.factors["map"] > 2.0


# -- the calibration flip (acceptance) -----------------------------------


def _gemm(seed=5, n=64):
    rng = np.random.RandomState(seed)
    a = da.from_numpy(rng.rand(n, n).astype(np.float32),
                      tiling=tiling_mod.row(2))
    b = da.from_numpy(rng.rand(n, n).astype(np.float32),
                      tiling=tiling_mod.row(2))
    return lambda: st.dot(st.as_expr(a), st.as_expr(b))


def _best(build):
    costs = tiling_cost.gemm_plan_costs(build())
    (_node, ranked), = costs.items()
    t, s, _cost = ranked[0]
    return t.axes, s


def test_calibration_flips_dp_choice_with_plan_key_separation():
    """The synthetic skewed-cost workload: measurements say output
    all-reduces (psum) cost ~10x what the uncalibrated model charges.
    The profile FITTED from those measurements must flip the tiling
    DP's GEMM strategy (psum-merged contraction -> gathered operands),
    re-key the plan, and leave the numerics unchanged."""
    build = _gemm()
    grid0, strat0 = _best(build)
    assert strat0 is not None  # uncalibrated: psum-merged contraction
    key0 = base.plan_signature(build())[0]

    # ledger entries measured under the skewed truth -> fitted profile
    _synthetic_rows({"map": 1.0, "contraction": 1.0, "reshard": 1.0,
                     "psum": 10.0})
    prof = ledger.fit_profile()
    assert prof.factors["psum"] / prof.factors["reshard"] > 7.0
    ledger.set_profile(prof)
    FLAGS.cost_calibration = True

    grid1, strat1 = _best(build)
    assert (grid1, strat1) != (grid0, strat0)
    assert strat1 is None  # calibrated: gather operands, skip the psum

    # plan-key separation: calibrated plans never alias uncalibrated
    key1 = base.plan_signature(build())[0]
    assert key0 != key1
    v1 = np.asarray(build().glom())
    FLAGS.cost_calibration = False
    key2 = base.plan_signature(build())[0]
    assert key2 == key0
    v0 = np.asarray(build().glom())
    np.testing.assert_allclose(v0, v1, rtol=1e-5)


def test_calibration_without_profile_is_identity():
    build = _gemm(seed=6)
    best0 = _best(build)
    FLAGS.cost_calibration = True  # on, but no profile installed
    assert ledger.factors() is None
    assert _best(build) == best0


def test_fit_profile_prefers_device_columns():
    """Entries carrying device columns (obs/profile sampled
    attribution) contribute per-class rows — the fitted factors track
    WHERE the device spent time, not one blended dispatch wall — and
    the profile's meta records the device-time provenance."""
    # predicted: map and reduce cost the same; measured device time:
    # map is 4x hotter than reduce
    ledger.ingest("dev-plan", {"map": 100.0, "reduce": 100.0}, 0.005)
    ledger.note_device_profile(
        "dev-plan", "replay", wall_s=0.005, attributed_s=0.005,
        class_seconds={"map": 0.004, "reduce": 0.001})
    prof = ledger.fit_profile()
    assert prof is not None
    assert prof.meta["source"] == "device_time"
    assert prof.meta["device_rows"] == 2
    ratio = prof.factors["map"] / prof.factors["reduce"]
    assert 3.0 < ratio < 5.0  # the 4x device skew, not the blend


def test_fit_profile_host_wall_fallback_source():
    """Entries WITHOUT device columns still fit from dispatch wall,
    and the profile says so (v2 provenance, satellite 6)."""
    ledger.ingest("host-plan-a", {"map": 100.0}, 0.002)
    ledger.ingest("host-plan-b", {"reduce": 100.0}, 0.001)
    prof = ledger.fit_profile()
    assert prof is not None
    assert prof.meta["source"] == "host_wall"
    assert prof.meta["device_rows"] == 0
