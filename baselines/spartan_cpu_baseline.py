"""8-process CPU Spartan-equivalent baseline harness.

SURVEY.md §6: "implement the 8-process CPU Spartan-equivalent baseline
(NumPy tiles) so the 10x target has a measured denominator." This mirrors
the reference's execution model (SURVEY.md §1 'owner-computes over
tiles'): a master process partitions arrays into tiles, ships per-tile
NumPy kernels to worker processes, workers fetch remote operand tiles
(pickled over pipes — the RPC-serialization cost the reference paid over
ZeroMQ), compute with NumPy, and send result tiles back for
reducer-merge/assembly.

Run: python baselines/spartan_cpu_baseline.py  -> writes cpu_baseline.json
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import time
from typing import Dict, List, Tuple

import numpy as np

N_WORKERS = 8


def _worker_dot(args):
    """Per-tile GEMM kernel: receives its A row-tile and the full B
    (the reference's kernel fetched B tile-rows via blob_ctx.get —
    SURVEY.md §3.3); returns the C row-tile."""
    a_tile, b = args
    return np.dot(a_tile, b)


def _worker_map_sum(args):
    """Config 1 kernel: fused elementwise chain + local sum per tile;
    partials reducer-merged by the master (SURVEY.md §3.2)."""
    x_tile, y_tile = args
    return float(((x_tile + y_tile) * 3.0 - x_tile).sum())


def _worker_kmeans(args):
    """Per-tile k-means kernel: assign + partial sums/counts
    (SURVEY.md §3.4)."""
    pts, centers = args
    d2 = ((pts[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    assign = d2.argmin(1)
    k = centers.shape[0]
    sums = np.zeros_like(centers)
    np.add.at(sums, assign, pts)
    counts = np.bincount(assign, minlength=k).astype(np.float64)
    return sums, counts


def _row_tiles(x: np.ndarray, n: int) -> List[np.ndarray]:
    return np.array_split(x, n, axis=0)


def bench_dot(pool, n: int = 4096, reps: int = 1) -> Dict:
    rng = np.random.RandomState(0)
    a = rng.rand(n, n).astype(np.float32)
    b = rng.rand(n, n).astype(np.float32)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        tiles = _row_tiles(a, N_WORKERS)
        # each worker receives (A_tile, B): B is 'fetched' by every
        # worker exactly as the reference's dot kernel fetched B tiles
        out = pool.map(_worker_dot, [(t, b) for t in tiles])
        c = np.concatenate(out, axis=0)
        times.append(time.perf_counter() - t0)
    best = min(times)
    gflops = 2.0 * n * n * n / best / 1e9
    assert c.shape == (n, n)
    return {"seconds": best, "gflops": gflops, "n": n}


def bench_map_sum(pool, n: int = 4096, reps: int = 2) -> Dict:
    rng = np.random.RandomState(1)
    x = rng.rand(n, n).astype(np.float32)
    y = rng.rand(n, n).astype(np.float32)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        xt = _row_tiles(x, N_WORKERS)
        yt = _row_tiles(y, N_WORKERS)
        partials = pool.map(_worker_map_sum, list(zip(xt, yt)))
        total = sum(partials)
        times.append(time.perf_counter() - t0)
    best = min(times)
    # 3 elementwise ops + reduction ≈ 4 flops/element
    gflops = 4.0 * n * n / best / 1e9
    return {"seconds": best, "gflops": gflops, "n": n, "result": total}


def bench_kmeans(pool, n: int = 125_000, d: int = 128, k: int = 64,
                 iters: int = 1, target_n: int = 1_000_000) -> Dict:
    """Measured at n points, linearly extrapolated to target_n (the
    per-point work is embarrassingly parallel, so the scaling is linear;
    this box has 1 CPU core, making the full 1M x 128 config impractical
    to time directly)."""
    rng = np.random.RandomState(2)
    pts = rng.rand(n, d).astype(np.float32)
    centers = pts[rng.choice(n, k, replace=False)].copy()
    tiles = _row_tiles(pts, N_WORKERS)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pool.map(_worker_kmeans, [(t, centers) for t in tiles])
        sums = sum(o[0] for o in out)
        counts = sum(o[1] for o in out)
        centers = (sums / np.maximum(counts, 1)[:, None]).astype(np.float32)
    dt = (time.perf_counter() - t0) / iters
    scale = target_n / n
    return {"sec_per_iter_measured": dt, "n_measured": n,
            "sec_per_iter_1m_extrapolated": dt * scale,
            "iters_per_sec_1m": 1.0 / (dt * scale),
            "d": d, "k": k, "target_n": target_n}


def _worker_pagerank(args):
    """Per-tile sparse kernel: local CSR partial SpMV (the reference's
    sparse tiles were scipy.sparse — SURVEY.md §2.2)."""
    import scipy.sparse as sp

    csr_tile, rank = args
    return csr_tile @ rank


def bench_pagerank(pool, n: int = 1_000_000, deg: int = 16,
                   iters: int = 3) -> Dict:
    """Config 5 denominator: row-tiled CSR SpMV + teleport, rank vector
    shipped to every worker each iteration (the per-tile fetch cost)."""
    import scipy.sparse as sp

    rng = np.random.RandomState(3)
    rows = np.repeat(np.arange(n), deg)
    cols = rng.randint(0, n, n * deg)
    m = sp.csr_matrix((np.ones(n * deg, np.float32), (rows, cols)),
                      shape=(n, n)).T.tocsr()
    bounds = np.linspace(0, n, N_WORKERS + 1).astype(int)
    tiles = [m[bounds[i]:bounds[i + 1]] for i in range(N_WORKERS)]
    rank = np.full(n, 1.0 / n, np.float32)
    t0 = time.perf_counter()
    for _ in range(iters):
        parts = pool.map(_worker_pagerank, [(t, rank) for t in tiles])
        y = np.concatenate(parts)
        rank = (0.85 * y + 0.15 / n).astype(np.float32)
        rank += (1.0 - rank.sum()) / n
    dt = (time.perf_counter() - t0) / iters
    return {"sec_per_iter": dt, "n": n, "edges": n * deg}


def _worker_logreg(args):
    x_tile, y_tile, w = args
    p = 1.0 / (1.0 + np.exp(-(x_tile @ w)))
    return x_tile.T @ (p - y_tile)


def bench_logreg(pool, n: int = 1_250_000, d: int = 32, iters: int = 2,
                 target_n: int = 10_000_000) -> Dict:
    """Config 4 denominator, measured at n rows and extrapolated to 10M
    (per-row work; 1-core box)."""
    rng = np.random.RandomState(4)
    x = rng.rand(n, d).astype(np.float32)
    y = (rng.rand(n) > 0.5).astype(np.float32)
    xt = _row_tiles(x, N_WORKERS)
    yt = _row_tiles(y, N_WORKERS)
    w = np.zeros(d, np.float32)
    t0 = time.perf_counter()
    for _ in range(iters):
        grads = pool.map(_worker_logreg,
                         [(a, b, w) for a, b in zip(xt, yt)])
        w = w - 0.1 * sum(grads) / n
    dt = (time.perf_counter() - t0) / iters
    scale = target_n / n
    return {"sec_per_iter_measured": dt, "n_measured": n,
            "sec_per_iter_10m_extrapolated": dt * scale, "d": d}


def main() -> None:
    out_path = os.path.join(os.path.dirname(__file__), "cpu_baseline.json")
    with mp.Pool(N_WORKERS) as pool:
        results = {
            "workers": N_WORKERS,
            "dot_4096": bench_dot(pool),
            "map_sum_4096": bench_map_sum(pool),
            "kmeans_1m": bench_kmeans(pool),
            "pagerank_1m": bench_pagerank(pool),
            "logreg_10m": bench_logreg(pool),
        }
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
